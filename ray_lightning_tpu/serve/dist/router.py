"""Load-aware router for the disaggregated serving fleet.

One router fronts N decode replicas and M prefill workers (ISSUE 12 /
ROADMAP item 1 — the multi-replica half of "serve heavy traffic").
Clients talk to it exactly as they talk to a single engine — the same
``serve_request`` wire items on :meth:`Router.queue_handle`, replies
streamed straight from whichever replica serves them to the client's
reply queue (the router is on the SUBMISSION path only; token streams
never funnel through it).

Responsibilities, all jax-free host logic:

* **admission** — the router tracks every request it routed until a
  terminal status comes back on a replica beat, so per-replica load is
  router-side truth, not a stale gauge.  When every live replica is at
  capacity (``num_slots + max_queue``), submission gets the typed
  ``rejected`` reply — the same backpressure contract a single engine
  gives, fleet-wide;
* **placement** — least-loaded by in-flight count (free-block and
  slot-occupancy gauges from the latest ``ServeStats`` beat snapshot
  break ties), with stickiness: a request re-routed after a prefill
  failure prefers the replica it was already bound to, ``spec>0``
  requests are placed only on draft-capable replicas, and
  ``adapter=`` requests only on pool-capable members — preferring
  ones already HOLDING the tenant's factors (the beat advertises
  them), hot-loading via :meth:`Router.register_adapter` blobs
  otherwise (a ``serve_adapter_load`` frame down the member's ordered
  inbox lane, so the load always lands before the dispatch);
* **prefill dispatch** — with prefill workers registered, a routed
  request first goes to the least-busy worker
  (``serve_prefill_dispatch``), which runs the prompt and ships the KV
  blocks straight to the chosen replica's inbox
  (``serve_kv_handoff``).  No workers = direct submission (the
  monolith-within-disagg baseline);
* **fault tolerance** — replica/worker liveness is beat-based
  (``lost_after_s`` without a beat, or the process handle reports
  dead).  A dead DECODE replica fails over: its in-flight requests are
  re-submitted to survivors through the engines' recompute-preemption
  path — the fleet-wide ``sample_seed`` the router stamped at
  admission makes the re-emitted stream bitwise-identical at any
  temperature, and clients dedup on token index, so no request is
  lost.  A dead PREFILL worker is respawned under the sliding-window
  :class:`RestartGovernor` (the restart-governance policy of the
  training plane, serve-shaped) and its pending prompts re-dispatched.
  Either death triggers an ``rlt-kv`` stale-segment sweep so dead
  handoffs never leak tmpfs.

Telemetry: :meth:`snapshot` is schema-pinned
(``telemetry/schema.py::validate_router_snapshot``), exported as
``router-live.json`` + the per-replica-labelled ``rlt_serve_*``
OpenMetrics family (``telemetry/export_prom.py``), and rendered by the
``rlt_top`` router pane.

Sends are ASYNCHRONOUS: every destination (member inbox or client
reply queue) gets a :class:`~.handoff.MemberOutbox` — a per-address
send thread with a bounded queue — so the control plane never blocks
inside a TCP connect to a wedged host (the PR-12 documented limit: a
blackholed member could hold the router lock for a full ~60s connect
timeout).  A failed or backed-up outbox reports once, and the router
routes the incident through the SAME death/failover path a
synchronous send failure used to take.

Distributed tracing (``telemetry_dir`` set): the router is where a
request's trace is BORN — ``trace_id`` is the rid, the root span id is
derived (``<rid>.root``), so failover re-submissions and recompute
replays land in the same trace with no registry.  The router records
the ``placement`` span (submit → dispatch frame on the wire, measured
in the outbox thread — real dispatch latency, not lock convoy), a
``failover`` span per re-routed request linked under the request root,
and the root ``request`` span at completion; per-rank exports stitch
via ``telemetry/trace_collect.py``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_lightning_tpu.serve.dist.handoff import (
    MemberOutbox, make_cancel_item, make_dispatch_item, request_fields,
)
from ray_lightning_tpu.telemetry.propagate import (
    child_context, root_context, trace_args,
)

__all__ = ["Router", "RestartGovernor"]

log = logging.getLogger(__name__)


class RestartGovernor:
    """Sliding-window restart budget (the strategy layer's restart
    governance, serve-shaped): at most ``max_restarts`` permits per
    trailing ``window_s``.  A worker that dies once a day respawns
    forever; a crash-looping one exhausts the window and stays down —
    loudly, via the router's ``prefill_respawns_denied`` counter."""

    def __init__(self, max_restarts: int = 3, window_s: float = 3600.0):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0: {max_restarts}")
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._attempts: List[float] = []

    def permit(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self._attempts = [t for t in self._attempts
                          if now - t < self.window_s]
        if len(self._attempts) >= self.max_restarts:
            return False
        self._attempts.append(now)
        return True


class _Member:
    """Router-side record of one fleet member (decode replica or
    prefill worker)."""

    def __init__(self, handle, role: str):
        self.handle = handle
        self.role = role
        self.id: str = handle.id
        self.inbox: Optional[Tuple[str, int]] = None
        self.caps: Dict[str, Any] = {}
        self.registered_t = time.monotonic()
        self.last_beat: Optional[float] = None
        self.snapshot: Dict[str, Any] = {}
        self.recompiles: Optional[int] = None
        # LoRA tenants this member holds: beat-advertised truth,
        # optimistically extended when the router sends a load frame
        # (the next beat confirms or corrects it).
        self.adapters: Set[str] = set()
        self.alive = True
        # Live-migration claim: a draining replica's ``migrating`` beat
        # names the rid set whose KV export is in flight.  Until the
        # claim expires (or every claimed rid's migration frame lands),
        # beat-loss failover is SUPPRESSED for this member — the
        # device->host gather of a full KV cache can exceed
        # ``lost_after_s``, and declaring the exporter dead mid-export
        # would race a recompute failover against the incoming
        # migration frames for the same rids.
        self.migrating_until: float = 0.0
        self.migrating_rids: Set[str] = set()

    def beat_age_s(self, now: float) -> float:
        return now - (self.last_beat
                      if self.last_beat is not None else self.registered_t)


class _Track:
    """One routed request until a terminal status comes back."""

    __slots__ = ("req", "replica", "worker", "resubmits", "t0",
                 "t_wall", "trace", "hedge_replica")

    def __init__(self, req: Dict[str, Any], t0: float):
        self.req = req
        self.replica: Optional[str] = None
        self.worker: Optional[str] = None
        self.resubmits = 0
        self.t0 = t0
        self.t_wall = time.time()
        self.trace = None  # the request's root TraceContext (tracing on)
        # Second placement of the SAME rid/seed on a different replica
        # (client-triggered hedge against a tail-latency straggler).
        # First terminal report wins; the other placement gets a
        # serve_cancel.  Also the hot spare: if the primary dies, the
        # hedge placement is promoted instead of a recompute failover.
        self.hedge_replica: Optional[str] = None


class Router:
    """The disaggregated fleet's front door (see module docstring)."""

    def __init__(
        self,
        *,
        lost_after_s: float = 2.0,
        hello_grace_s: float = 120.0,
        governor: Optional[RestartGovernor] = None,
        prefill_factory: Optional[Callable[[], Any]] = None,
        telemetry_dir: Optional[str] = None,
        trace_dir: Optional[str] = None,
        prom_file: Optional[str] = None,
        prom_port: Optional[int] = None,
        export_every_s: float = 1.0,
        poll_interval_s: float = 0.02,
        headroom_routing: Optional[bool] = None,
        migration_claim_s: float = 30.0,
        brownout=None,
    ):
        from ray_lightning_tpu.cluster.queue import DriverQueue

        # Heartbeat-lost threshold: a replica whose beats stop for this
        # long is declared dead and failed over.  The hello grace covers
        # member startup (actor spawn + model build) before first beat.
        self.lost_after_s = lost_after_s
        self.hello_grace_s = hello_grace_s
        self.governor = governor or RestartGovernor()
        self._prefill_factory = prefill_factory
        self._beats = DriverQueue()
        self._requests = DriverQueue()
        # Fleet/request state shared between the poll thread,
        # submitters, and outbox error callbacks.
        self._replicas: Dict[str, _Member] = {}  # guarded by self._lock
        self._workers: Dict[str, _Member] = {}   # guarded by self._lock
        self._inflight: Dict[str, _Track] = {}   # guarded by self._lock
        # Failover re-submissions that found every candidate saturated:
        # retried each poll — a failed-over request is never dropped.
        self._retry: deque = deque()             # guarded by self._lock
        self.counters: Dict[str, int] = {
            "routed": 0, "completed": 0, "rejected": 0, "expired": 0,
            "invalid": 0, "failovers": 0, "failed_over_requests": 0,
            "prefill_dispatches": 0, "direct_submits": 0,
            "replica_deaths": 0, "worker_deaths": 0,
            "replica_drains": 0, "worker_drains": 0,
            "prefill_respawns": 0, "prefill_respawns_denied": 0,
            "adapter_loads_sent": 0, "prefix_affinity_hits": 0,
            "migrations": 0, "migration_reroutes": 0,
            "hedges": 0, "hedge_cancels": 0,
            "shed": 0, "cancelled": 0,
        }
        # Prefix-affinity map: (adapter, leading-token) key -> the
        # replica that last served a prompt with that prefix, so
        # shared-prefix traffic lands where the resident chain lives
        # (the replica-side PrefixIndex turns the affinity into claimed
        # blocks).  Bounded LRU — placement metadata, never
        # correctness: a stale or evicted entry just means one cold
        # prefill.  guarded by self._lock
        self._prefix_sticky: "OrderedDict[Any, str]" = OrderedDict()
        # Multi-tenant LoRA registry: name -> {"rank", "data"} (the
        # encode_adapter blob, encoded ONCE at registration) — the
        # source the router hot-loads members from on demand.
        self._adapters: Dict[str, Dict[str, Any]] = {}  # guarded by self._lock
        # Staleness of the last dead replica's final beat at detection —
        # the failover-latency component the router can observe.
        self.last_failover_detect_s: Optional[float] = None
        self._seed_counter = 0
        # One MemberOutbox per destination address (member inboxes AND
        # client reply queues): all wire writes leave the lock.  Idle
        # lanes are reaped (clients come and go; re-creation on the
        # next send is one TCP connect) and _closing gates creation
        # during stop().
        # guarded by self._lock
        self._outboxes: Dict[Tuple[str, int], MemberOutbox] = {}
        self._outbox_idle_s = 120.0
        self._closing = False                    # guarded by self._lock
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._poll_interval_s = poll_interval_s
        self._export_every_s = export_every_s
        self._last_export = 0.0
        self._live_path = None
        self._trace_path = None
        self._exporter = None
        if telemetry_dir:
            import os

            os.makedirs(telemetry_dir, exist_ok=True)
            self._live_path = f"{telemetry_dir}/router-live.json"
        if trace_dir:
            import os

            os.makedirs(trace_dir, exist_ok=True)
            self._trace_path = f"{trace_dir}/trace-router.jsonl"
        from ray_lightning_tpu.telemetry.spans import SpanTracer

        # Wall-clock tracer: router spans stitch against worker/replica
        # exports by shared epoch (trace_collect.py).  Gated on
        # trace_dir like every other component — a telemetry-only
        # fleet's wire frames stay byte-identical to pre-trace rounds.
        self.tracer = SpanTracer(
            enabled=self._trace_path is not None, maxlen=16384,
            rank=0, clock=time.time,
        )
        if prom_file or prom_port is not None:
            from ray_lightning_tpu.telemetry.export_prom import PromExporter

            self._exporter = PromExporter(textfile=prom_file,
                                          port=prom_port)
        # Headroom-aware placement tie-break (capacity plane): between
        # equally-assigned candidates, prefer the replica whose
        # headroom oracle reports the most tokens/s slack — measured
        # throughput beats the raw free-block proxy once beats carry
        # capacity blocks.  OFF by default; the flag (or
        # RLT_HEADROOM_ROUTING=1) only REORDERS ties, it never admits
        # or rejects, so routing stays correct if beats lack the block.
        if headroom_routing is None:
            import os

            headroom_routing = \
                os.environ.get("RLT_HEADROOM_ROUTING", "0") == "1"
        self._headroom_routing = bool(headroom_routing)
        # How long a ``migrating`` beat claim suppresses beat-loss
        # failover for the draining replica (the export of a full KV
        # residency can take many seconds; an expired claim falls back
        # to recompute failover for whatever never arrived).
        self.migration_claim_s = migration_claim_s
        # Overload brownout ladder (capacity plane -> admission):
        # OFF unless passed explicitly or RLT_BROWNOUT=1.  When on,
        # fleet utilization from beat capacity blocks drives staged
        # degradation in submit_request — spec off, max_new capped,
        # then priority-class shedding with a half-open recovery probe.
        if brownout is None:
            import os

            if os.environ.get("RLT_BROWNOUT", "0") == "1":
                from ray_lightning_tpu.serve.brownout import BrownoutLadder

                brownout = BrownoutLadder()
        self.brownout = brownout
        self._brownout_last_level = 0
        # Fleet trend store, created lazily on the first beat carrying
        # a capacity block: per-replica tokens_out counters + headroom
        # gauges, the sensing input ROADMAP item 4's fleet scheduler
        # reads.  None until a capacity-plane member reports.
        self.timeseries = None                   # guarded by self._lock

    # -- fleet membership ----------------------------------------------------
    @property
    def beat_handle(self):
        """Picklable handle members publish hellos/beats to."""
        return self._beats.handle

    def queue_handle(self):
        """Picklable submission handle for :class:`ServeClient` — the
        router speaks the engine's wire dialect."""
        return self._requests.handle

    def add_replica(self, handle) -> None:
        with self._lock:
            self._replicas[handle.id] = _Member(handle, "decode")

    def add_prefill(self, handle) -> None:
        with self._lock:
            self._workers[handle.id] = _Member(handle, "prefill")

    def register_adapter(self, name: str, adapter: Dict[str, Any]) -> None:
        """Register one tenant's LoRA adapter with the fleet: the
        factors are encoded ONCE (``serve/lora.py::encode_adapter``)
        and kept host-side; members are hot-loaded lazily, at the
        moment a request for the tenant is placed on one that does not
        yet hold it.  Registration is cheap and does not touch any
        member — a registered-but-idle tenant costs the fleet nothing
        until its first request.

        Re-registering an existing name updates the ROUTER's blob only:
        members already advertising the tenant keep their loaded
        factors (the engines refuse live replacement anyway — see
        ``ServeEngine.add_adapter``).  To roll a tenant's factors,
        drain the tenant, remove it on the members, then register the
        new version."""
        from ray_lightning_tpu.serve.lora import encode_adapter

        name = str(name)
        rank = int(adapter["qkv_a"].shape[-1])
        data = encode_adapter(adapter)
        with self._lock:
            self._adapters[name] = {"rank": rank, "data": data}

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until every registered member has hello'd its inbox."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll()
            with self._lock:
                members = (list(self._replicas.values())
                           + list(self._workers.values()))
                if members and all(m.inbox is not None for m in members
                                   if m.alive):
                    return
            time.sleep(0.02)
        raise TimeoutError(
            "serve fleet members did not register within "
            f"{timeout}s (actor startup wedged?)"
        )

    # -- the poll loop -------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> None:
        """One control-plane iteration: drain member beats, drain
        client submissions, detect deaths (failover/respawn), retry
        deferred failovers, refresh exports."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._drain_beats(now)
            self._update_brownout(now)
            self._drain_requests(now)
            self._check_liveness(now)
            self._drain_retry(now)
            self._maybe_export()
        self._reap_idle_outboxes(now)

    def start(self) -> "Router":
        if self._thread is not None:
            raise RuntimeError("router already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="rlt-serve-router", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:  # noqa: BLE001 - the control plane must
                # survive a bad frame; the failure mode to avoid is a
                # silently dead router stranding every client
                log.warning("router poll raised", exc_info=True)
            time.sleep(self._poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._beats.shutdown()
        self._requests.shutdown()
        # Flag-then-snapshot under the lock: a concurrent outbox-error
        # death path re-routing through _put must not register a fresh
        # outbox AFTER the clear (its thread would leak).
        with self._lock:
            self._closing = True
            boxes = list(self._outboxes.values())
            self._outboxes.clear()
        for box in boxes:
            box.close()
        if self._exporter is not None:
            self._exporter.close()
        if self._trace_path is not None and self.tracer.events():
            try:
                self.tracer.export_jsonl(self._trace_path)
            except OSError:
                pass  # a full disk must not fail the teardown
        self._sweep_segments()

    # -- beats ---------------------------------------------------------------
    def _member(self, role: str,
                member_id: str) -> Optional[_Member]:  # rlt: holds self._lock
        pool = self._replicas if role == "decode" else self._workers
        return pool.get(member_id)

    def _drain_beats(self, now: float) -> None:  # rlt: holds self._lock
        import queue as _pyqueue

        while True:
            try:
                item = self._beats.get_nowait()
            except _pyqueue.Empty:
                return
            if not isinstance(item, dict):
                continue
            kind = item.get("type")
            if kind == "serve_replica_hello":
                m = self._member(str(item.get("role")), str(item.get("id")))
                if m is not None:
                    m.inbox = (item["inbox"][0], int(item["inbox"][1]))
                    m.caps = {k: v for k, v in item.items()
                              if k not in ("type", "role", "id", "inbox")}
                    m.last_beat = now
            elif kind == "serve_replica_beat":
                self._ingest_beat(item, now)
            elif kind == "serve_migration":
                # Live-KV migration frames ride the ordered beat lane
                # (FIFO per connection: claim beat -> migration frames
                # -> closing beat), so every migrated rid is retargeted
                # BEFORE the closing beat re-places the leftovers.
                self._on_migration(item, now)

    def _ingest_beat(self, item: Dict[str, Any],
                     now: float) -> None:  # rlt: holds self._lock
        m = self._member(str(item.get("role")), str(item.get("id")))
        if m is None:
            return
        m.last_beat = now
        if "snapshot" in item:
            m.snapshot = item["snapshot"]
            cap = m.snapshot.get("capacity") \
                if isinstance(m.snapshot, dict) else None
            if isinstance(cap, dict):
                if self.timeseries is None:
                    from ray_lightning_tpu.telemetry.timeseries import (
                        TimeSeriesStore,
                    )

                    self.timeseries = TimeSeriesStore(
                        interval_s=1.0, capacity=600,
                    )
                counters = m.snapshot.get("counters", {})
                self.timeseries.observe(
                    f"{m.id}.tokens_out",
                    counters.get("tokens_out", 0), kind="counter",
                )
                head = cap.get("headroom_tokens_per_s")
                if isinstance(head, (int, float)):
                    self.timeseries.observe(
                        f"{m.id}.headroom_tokens_per_s", head,
                        kind="gauge",
                    )
        if "recompiles" in item:
            m.recompiles = int(item["recompiles"])
        if "adapters" in item:
            # Beat-advertised truth replaces the optimistic set — a
            # member that dropped a load frame (restart, full pool)
            # stops being preferred for that tenant within one beat.
            m.adapters = {str(a) for a in item["adapters"]}
        if "migrating" in item:
            # A drain's export claim: suppress beat-loss failover for
            # this member while the gather runs (see _is_lost) and
            # remember which rids are promised — each arriving
            # migration frame checks one off.
            m.migrating_rids = {str(r) for r in item["migrating"]}
            m.migrating_until = now + self.migration_claim_s
        for rid, status in item.get("done", []):
            if m.role == "decode":
                self._complete(str(rid), str(status), source=m.id)
            else:
                track = self._inflight.get(str(rid))
                if track is not None and track.worker == m.id:
                    track.worker = None  # handoff landed; replica owns it
        for rid, err in item.get("failed", []):
            track = self._inflight.get(str(rid))
            # Ownership guard (mirrors the done-loop above): a stale
            # failure report from a member this rid was already routed
            # AWAY from must not yank the request off its healthy new
            # placement.  Prefill workers report undeliverable
            # handoffs; decode replicas report handoffs they could not
            # ADMIT (torn frame, injected read fault) — both re-route
            # away from the replica that was supposed to decode.
            if track is None:
                continue
            if track.worker == m.id or (m.role == "decode"
                                        and track.replica == m.id):
                self._on_handoff_failure(str(rid), str(err), now)
        if item.get("closing") and m.alive:
            self._on_member_closing(m, now)

    def _complete(self, rid: str, status: str,
                  source: Optional[str] = None) -> None:  # rlt: holds self._lock
        track = self._inflight.pop(rid, None)
        if track is None:
            return
        key = status if status in ("rejected", "expired", "invalid",
                                   "cancelled") \
            else "completed"
        self.counters[key] += 1
        if track.hedge_replica is not None and status != "cancelled":
            # First terminal report wins the hedged pair; the OTHER
            # placement gets a serve_cancel so it stops burning slots
            # (its own later "cancelled" done lands after the pop and
            # is a no-op).  The client deduplicates both token streams
            # by index — same rid, same fleet seed, identical tokens.
            loser_id = track.replica if source == track.hedge_replica \
                else track.hedge_replica
            loser = self._replicas.get(loser_id) \
                if loser_id is not None else None
            if loser is not None and loser.alive \
                    and loser.inbox is not None:
                try:
                    self._put(loser.inbox, make_cancel_item(rid))
                    self.counters["hedge_cancels"] += 1
                except (OSError, ConnectionError):
                    pass  # loser is dying; its death path cleans up
        if track.trace is not None:
            # The root span anchors the whole trace: every downstream
            # span's parent chain terminates at <rid>.root.
            self.tracer.record(
                "request", track.t_wall,
                max(0.0, time.time() - track.t_wall),
                args=trace_args(track.trace, rid=rid, status=status,
                                resubmits=track.resubmits),
            )

    def _on_member_closing(self, m: _Member,
                           now: float) -> None:  # rlt: holds self._lock
        """Planned member drain (the ``closing`` flag on a final beat —
        an operator scale-down, NOT a crash): stop routing to it and
        re-place its remaining work, without burning failure counters,
        respawn budget, or a spurious ``failovers`` increment in the
        telemetry surface.  The member's own teardown (engine stop +
        segment sweep) is the operator's — no reap here."""
        m.alive = False
        # Rids the drain's live migration already retargeted have
        # track.replica pointing at their survivor (migration frames
        # ride the same ordered lane, AHEAD of this closing beat) — the
        # selector below naturally skips them.  What's left is the
        # un-migratable tail: queued or mid-chunked-prefill requests,
        # and exports the fault plane blackholed.
        remaining = [rid for rid, t in self._inflight.items()
                     if (t.replica if m.role == "decode" else t.worker)
                     == m.id]
        log.info("serve %s %s draining (planned) — re-placing %d "
                 "request(s)", m.role, m.id, len(remaining))
        self.counters["replica_drains" if m.role == "decode"
                      else "worker_drains"] += 1
        for rid in remaining:
            track = self._inflight[rid]
            track.worker = None
            if m.role == "decode":
                track.replica = None
                if track.hedge_replica is not None \
                        and track.hedge_replica != m.id:
                    # The hedge placement is already decoding this rid
                    # elsewhere — promote it, skip the recompute.
                    track.replica, track.hedge_replica = \
                        track.hedge_replica, None
                    continue
                track.hedge_replica = None
            track.resubmits += 1
            self._route(rid, track, now,
                        exclude={m.id} if m.role == "decode"
                        else frozenset(),
                        must_place=True)
        if m.role == "decode":
            for t in self._inflight.values():
                if t.hedge_replica == m.id:
                    t.hedge_replica = None  # primary still live
        self._sweep_segments()

    def _on_handoff_failure(self, rid: str, err: str,
                            now: float) -> None:  # rlt: holds self._lock
        """A prefill worker could not deliver to the chosen replica —
        trust the signal and re-route AWAY from it (if that replica is
        healthy, losing one placement is cheap; if it is dying, beats
        will confirm shortly)."""
        track = self._inflight.get(rid)
        if track is None:
            return
        exclude = {track.replica} if track.replica else set()
        track.worker = None
        track.replica = None
        track.resubmits += 1
        self._route(rid, track, now, exclude=exclude, must_place=True)

    # -- live-KV migration ---------------------------------------------------
    def _on_migration(self, item: Dict[str, Any],
                      now: float) -> None:  # rlt: holds self._lock
        """One ``serve_migration`` frame from a draining replica: pick
        a survivor, forward the frame (KV blocks + scheduler position +
        the original request fields ride inside), retarget the track.
        The survivor resumes decode mid-sequence — zero recomputed
        prefill, and the fleet-wide seed + position-keyed sampler keep
        the continued stream bitwise-identical at any temperature.  No
        viable survivor (or a failed adapter ensure) falls back to the
        recompute-failover path the crash plane already exercises."""
        rid = str(item.get("rid"))
        track = self._inflight.get(rid)
        source = track.replica if track is not None else None
        # Check the rid off its source's claim set either way — a frame
        # that landed is a promise kept, even if the track is gone.
        for m in self._replicas.values():
            m.migrating_rids.discard(rid)
            if not m.migrating_rids and m.migrating_until:
                # Every promised frame arrived: release the failover
                # suppression early instead of waiting out the claim.
                m.migrating_until = 0.0
        if track is None:
            log.debug("migration frame for unknown rid %s dropped", rid)
            return
        req = item.get("req") or {}
        adapter = req.get("adapter")
        survivors = [
            m for m in self._replicas.values()
            if m.alive and m.inbox is not None and m.id != source
            and self._assigned(m.id) < (m.caps.get("num_slots", 1)
                                        + m.caps.get("max_queue", 0))
        ]
        if adapter is not None:
            survivors = [
                m for m in survivors
                if m.caps.get("max_adapters", 0) > 0
                and (adapter in m.adapters or adapter in self._adapters)
            ]
        target = min(
            survivors,
            key=lambda m: (self._assigned(m.id),
                           -self._blocks_free(m), m.id),
        ) if survivors else None
        if target is not None and adapter is not None:
            try:
                self._ensure_adapter(target, adapter)
            except (OSError, ConnectionError):
                self._on_replica_death(target, now)
                target = None
        if target is not None:
            try:
                self._put(target.inbox, item)
            except (OSError, ConnectionError):
                self._on_replica_death(target, now)
                target = None
        if target is None:
            # Recompute fallback: re-route through the normal failover
            # path (prefill replays from token 0 on a survivor; the
            # client dedups the re-emitted indices).
            self.counters["migration_reroutes"] += 1
            track.worker = None
            track.replica = None
            track.resubmits += 1
            self._route(rid, track, now,
                        exclude={source} if source else frozenset(),
                        must_place=True)
            return
        track.replica = target.id
        track.worker = None
        self.counters["migrations"] += 1
        if track.trace is not None:
            self.tracer.record(
                "migration", time.time(), 0.0,
                args=trace_args(
                    child_context(track.trace), rid=rid,
                    from_replica=source, to_replica=target.id,
                ),
            )

    # -- client submissions --------------------------------------------------
    def _drain_requests(self, now: float) -> None:  # rlt: holds self._lock
        import queue as _pyqueue

        while True:
            try:
                item = self._requests.get_nowait()
            except _pyqueue.Empty:
                return
            try:
                self.submit_request(item, now=now)
            except Exception as e:  # noqa: BLE001 - a bad request must
                # never take the router down; when the reply address is
                # recoverable the client gets the engine's typed
                # "invalid" reply instead of blocking to its timeout
                log.warning("router: malformed request: %s", e)
                try:
                    rid = str(item.get("rid"))
                    reply = tuple(item["reply"])
                except Exception:  # noqa: BLE001 - nothing to tell
                    continue
                self.counters["invalid"] += 1
                self._reply(reply, {
                    "type": "serve_done", "rid": rid,
                    "status": "invalid", "error": str(e), "tokens": [],
                })

    def submit_request(self, item: Dict[str, Any],
                       now: Optional[float] = None) -> str:
        """Admit one ``serve_request`` wire item: stamp the fleet-wide
        sampling seed, validate against the fleet geometry, place it.
        Returns the rid; rejection/invalid outcomes reply to the
        client's queue exactly as a single engine would."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not isinstance(item, dict) \
                    or item.get("type") != "serve_request":
                raise ValueError("not a serve_request item")
            rid = str(item["rid"])
            reply = tuple(item["reply"])
            existing = self._inflight.get(rid)
            if existing is not None:
                # Re-submission of a rid the fleet already tracks: a
                # hedge marker places a DUPLICATE on another replica
                # (same seed — the client dedups both streams by token
                # index); anything else is a client retry racing its
                # own in-flight request and is dropped silently.
                if item.get("hedge"):
                    self._hedge(rid, existing, now)
                return rid
            seed = item.get("sample_seed")
            if seed is None:
                # The fleet-wide sampling-stream identity: stamped HERE
                # (not per engine) so a failover re-submission to any
                # replica replays the identical token stream.
                seed = self._seed_counter
                self._seed_counter += 1
            # Trace identity: the rid IS the trace_id, stamped once
            # here — every hop (prefill, handoff, decode, failover
            # re-submission, preemption replay) shares it.
            ctx = root_context(rid) if self.tracer.enabled else None
            req = request_fields(
                rid, item["prompt"], int(item["max_new_tokens"]),
                reply=reply, sample_seed=seed,
                temperature=float(item.get("temperature", 0.0)),
                eos_token_id=item.get("eos_token_id"),
                top_k=item.get("top_k"),
                spec=item.get("spec"),
                adapter=item.get("adapter"),
                deadline_s=item.get("deadline_s"),
                priority=int(item.get("priority") or 0),
                trace=ctx,
            )
            problem = self._validate(req)
            if problem is not None:
                self.counters["invalid"] += 1
                self._reply(reply, {
                    "type": "serve_done", "rid": rid, "status": "invalid",
                    "error": problem, "tokens": [],
                })
                return rid
            if self.brownout is not None and self.brownout.level > 0:
                # Staged overload degradation (ladder levels, each
                # subsuming the previous): 1 = drop speculative draft
                # lanes (spec FLOPs are the cheapest capacity to
                # reclaim), 2 = cap response length, 3 = shed
                # best-effort traffic (priority < 1) with a typed
                # retryable reply — except the half-open probe the
                # ladder lets through to sense recovery.
                lvl = self.brownout.level
                if req.get("spec"):
                    req["spec"] = 0
                if lvl >= 2:
                    cap = int(self.brownout.max_new_cap)
                    if req["max_new_tokens"] > cap:
                        req["max_new_tokens"] = cap
                if lvl >= 3 and int(req.get("priority") or 0) < 1 \
                        and not self.brownout.allow_probe(now):
                    self.counters["shed"] += 1
                    self._reply(reply, {
                        "type": "serve_done", "rid": rid,
                        "status": "shed", "reason": "brownout",
                        "tokens": [],
                    })
                    return rid
            track = _Track(req, now)
            track.trace = ctx
            self._inflight[rid] = track
            self.counters["routed"] += 1
            self._route(rid, track, now)
            return rid

    def _validate(self,
                  req: Dict[str, Any]) -> Optional[str]:  # rlt: holds self._lock
        """Cheap fleet-geometry validation so prefill workers never see
        a prompt they cannot bucket (the engines re-validate anyway)."""
        if not req["prompt"]:
            return "prompt must contain at least one token"
        if req["max_new_tokens"] < 1:
            return "max_new_tokens must be >= 1"
        # Live replicas only: a dead member's (possibly smaller) limits
        # must not keep rejecting prompts the surviving fleet serves.
        caps = [m.caps for m in self._replicas.values()
                if m.caps and m.alive]
        if caps:
            max_prompt = min(c.get("max_prompt_len", 1 << 30)
                             for c in caps)
            max_len = min(c.get("max_model_len", 1 << 30) for c in caps)
            if len(req["prompt"]) > max_prompt:
                return (f"prompt ({len(req['prompt'])}) exceeds the "
                        f"fleet's largest prefill bucket ({max_prompt})")
            if len(req["prompt"]) + req["max_new_tokens"] > max_len:
                return (f"prompt + max_new_tokens exceeds the fleet's "
                        f"max_model_len ({max_len})")
        adapter = req.get("adapter")
        if adapter is not None and adapter not in self._adapters \
                and not any(adapter in m.adapters
                            for m in self._replicas.values() if m.alive):
            # Typed, synchronous: an unknown tenant must never fall
            # back silently to the base model on some replica.
            return (f"unknown adapter {adapter!r} — register it with "
                    f"Router.register_adapter (or hot-load a replica) "
                    f"first")
        return None

    def _hedge(self, rid: str, track: _Track,
               now: float) -> None:  # rlt: holds self._lock
        """Place a DUPLICATE of an in-flight request on a second
        replica (client-triggered tail-latency hedge).  Same rid, same
        fleet-wide seed: both replicas emit the identical stream, the
        client dedups by token index, the first terminal report wins
        and the loser is cancelled (see _complete).  Hedging is
        best-effort — no spare capacity, an unplaced primary, or an
        existing hedge all make this a silent no-op (the primary
        placement is untouched either way)."""
        if track.hedge_replica is not None or track.replica is None:
            return
        req = track.req
        candidates = [
            m for m in self._replicas.values()
            if m.alive and m.inbox is not None and m.id != track.replica
            and self._assigned(m.id) < (m.caps.get("num_slots", 1)
                                        + m.caps.get("max_queue", 0))
        ]
        if req.get("spec"):
            candidates = [m for m in candidates
                          if m.caps.get("spec_k", 0) > 0]
        adapter = req.get("adapter")
        if adapter is not None:
            candidates = [
                m for m in candidates
                if m.caps.get("max_adapters", 0) > 0
                and (adapter in m.adapters or adapter in self._adapters)
            ]
        if not candidates:
            return
        target = min(
            candidates,
            key=lambda m: (self._assigned(m.id),
                           -self._blocks_free(m), m.id),
        )
        try:
            if adapter is not None:
                self._ensure_adapter(target, adapter)
            # Direct submission only: a hedge exists to beat a
            # straggler, re-running disaggregated prefill for it would
            # put the duplicate behind the same worker queue that may
            # be the straggle's cause.
            self._put(target.inbox, dict(req))
        except (OSError, ConnectionError):
            self._on_replica_death(target, now)
            return
        track.hedge_replica = target.id
        self.counters["hedges"] += 1

    def _update_brownout(self, now: float) -> None:  # rlt: holds self._lock
        """Feed the brownout ladder the fleet's beat-aggregated
        utilization (no capacity blocks -> no signal -> ladder stays
        where it is; it only moves on evidence)."""
        if self.brownout is None:
            return
        blocks = [
            m.snapshot.get("capacity") for m in self._replicas.values()
            if m.alive and isinstance(m.snapshot, dict)
            and isinstance(m.snapshot.get("capacity"), dict)
        ]
        if not blocks:
            return
        from ray_lightning_tpu.serve.capacity import aggregate_fleet

        fleet = aggregate_fleet(blocks)
        util = fleet.get("utilization") if fleet else None
        if not isinstance(util, (int, float)):
            return
        level = self.brownout.observe(float(util), now)
        if level != self._brownout_last_level:
            log.warning(
                "serve brownout level %d -> %d (fleet utilization "
                "%.2f)", self._brownout_last_level, level, util,
            )
            self._brownout_last_level = level

    # -- placement -----------------------------------------------------------
    def _assigned(self, replica_id: str) -> int:  # rlt: holds self._lock
        # Hedge placements occupy a slot on their replica exactly like
        # primaries — capacity accounting must see both.
        return sum(1 for t in self._inflight.values()
                   if replica_id in (t.replica, t.hedge_replica))

    def _pending(self, worker_id: str) -> int:  # rlt: holds self._lock
        return sum(1 for t in self._inflight.values()
                   if t.worker == worker_id)

    def _blocks_free(self, m: _Member) -> float:
        gauges = m.snapshot.get("gauges", {}) if m.snapshot else {}
        return float(gauges.get("blocks_free", 0.0))

    def _headroom(self, m: _Member) -> float:
        """Oracle-reported tokens/s slack from the member's last beat
        (0.0 when the member runs without the capacity plane)."""
        cap = m.snapshot.get("capacity") if m.snapshot else None
        if isinstance(cap, dict):
            head = cap.get("headroom_tokens_per_s")
            if isinstance(head, (int, float)):
                return float(head)
        return 0.0

    # Leading tokens hashed into the affinity key: enough to
    # distinguish system-prompt/template families, cheap enough to
    # compute per route.
    _PREFIX_KEY_TOKENS = 64
    _PREFIX_STICKY_CAP = 4096

    # rlt: holds self._lock
    def _prefix_key(self, req: Dict[str, Any]) -> Optional[Any]:
        prompt = req.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return None
        return (req.get("adapter"),
                hash(tuple(prompt[: self._PREFIX_KEY_TOKENS])))

    # rlt: holds self._lock
    def _note_prefix_sticky(self, key: Any, replica_id: str) -> None:
        self._prefix_sticky[key] = replica_id
        self._prefix_sticky.move_to_end(key)
        while len(self._prefix_sticky) > self._PREFIX_STICKY_CAP:
            self._prefix_sticky.popitem(last=False)

    # rlt: holds self._lock
    def _route(self, rid: str, track: _Track, now: float,
               exclude: Set[str] = frozenset(),
               must_place: bool = False) -> None:
        """Pick a replica (and a prefill worker when any are live) for
        ``rid``.  ``must_place`` marks failover/re-route submissions:
        instead of a typed rejection they park on the retry queue until
        capacity frees up — a request the fleet already accepted is
        never lost to a transient squeeze."""
        req = track.req
        if track.resubmits > 16:
            # Re-route budget: a legitimate failover chain burns one
            # resubmit per member death — far below this bound.  What
            # does hit it is a PERSISTENT per-request failure loop
            # (e.g. a member whose adapter pool is full raises on every
            # hot-load, the dispatch fails, the failed feed re-routes,
            # the next beat erases the optimistic adapters entry,
            # repeat) — without the bound that loop re-ships the blob
            # forever while the client blocks to its timeout.
            self._finish_unroutable(
                rid, track, "error",
                f"re-route budget exhausted after {track.resubmits} "
                f"attempts (persistent placement failure — check "
                f"member capacity, e.g. ServeConfig.max_adapters vs "
                f"registered tenants)",
            )
            return
        live = [m for m in self._replicas.values()
                if m.alive and m.inbox is not None and m.id not in exclude]
        spec = req.get("spec")
        if spec is not None and spec > 0:
            capable = [m for m in live if m.caps.get("spec_k", 0) > 0]
            if not capable:
                # A draft-less engine would fail the request as
                # "invalid" — never send a spec request there.  No
                # capable replica in the FLEET: terminal invalid.
                # Capable but not currently routable (excluded after a
                # transient handoff failure, or not hello'd yet): an
                # already-accepted request parks until it is, a fresh
                # one gets the typed retryable rejection.
                if any(m.caps.get("spec_k", 0) > 0
                       for m in self._replicas.values() if m.alive):
                    if must_place:
                        self._park(rid)
                    else:
                        self._finish_unroutable(
                            rid, track, "rejected",
                            "draft-capable replica temporarily "
                            "unavailable",
                        )
                else:
                    self._finish_unroutable(
                        rid, track, "invalid",
                        "spec > 0 but no draft-capable replica in "
                        "the fleet",
                    )
                return
            live = capable
        adapter = req.get("adapter")
        if adapter is not None:
            # Pool-capable replicas only; a pool-less engine would fail
            # the request as "invalid" (its submit raises on adapter=).
            # When the router holds the registered blob any capable
            # replica is loadable on demand; otherwise only members
            # already advertising the tenant can serve it.
            capable = [m for m in live if m.caps.get("max_adapters", 0) > 0]
            if adapter not in self._adapters:
                capable = [m for m in capable if adapter in m.adapters]
            if not capable:
                fleet_capable = any(
                    m.caps.get("max_adapters", 0) > 0
                    for m in self._replicas.values() if m.alive
                )
                if fleet_capable and adapter in self._adapters:
                    if must_place:
                        self._park(rid)
                    else:
                        self._finish_unroutable(
                            rid, track, "rejected",
                            "adapter-capable replica temporarily "
                            "unavailable",
                        )
                else:
                    self._finish_unroutable(
                        rid, track, "invalid",
                        f"adapter {adapter!r}: no adapter-capable "
                        f"replica holds it and no registered blob to "
                        f"hot-load from",
                    )
                return
            live = capable
        if not live:
            if must_place and any(m.alive for m in self._replicas.values()):
                self._park(rid)
                return
            self._finish_unroutable(
                rid, track,
                "error" if must_place else "rejected",
                "no live decode replica",
            )
            return
        candidates = [
            m for m in live
            if self._assigned(m.id) < (m.caps.get("num_slots", 1)
                                       + m.caps.get("max_queue", 0))
        ]
        if not candidates:
            if must_place:
                self._park(rid)
                return
            self.counters["rejected"] += 1
            self._inflight.pop(rid, None)
            self._reply(tuple(req["reply"]), {
                "type": "serve_done", "rid": rid, "status": "rejected",
                "reason": "rejected", "tokens": [],
            })
            return
        # Stickiness: a request already bound to a live replica (spec
        # drafts mid-re-route after a prefill hiccup) stays there — its
        # draft cache and its queue position are warm.
        target = next((m for m in candidates if m.id == track.replica),
                      None)
        pkey = self._prefix_key(req)
        if target is None:
            # Prefix affinity: prefer the replica that last served this
            # prompt family (its PrefixIndex holds the chain — the claim
            # turns the placement into skipped prefill FLOPs), behind
            # adapter residency (wrong-adapter placement costs a blob
            # ship, worse than a cold prefill) and ahead of load
            # balance (a cache hit is cheaper than an even spread).
            # Affinity never QUEUES, though: once the warm replica's
            # slots are full, waiting behind it costs more than a cold
            # prefill on an idle one — drop the pull and let the
            # least-loaded term place the request.
            sticky = self._prefix_sticky.get(pkey) \
                if pkey is not None else None
            if sticky is not None:
                sm = next((m for m in candidates if m.id == sticky),
                          None)
                if sm is None or (self._assigned(sm.id)
                                  >= sm.caps.get("num_slots", 1)):
                    sticky = None
            if self._headroom_routing:
                # Capacity-plane tie-break: oracle-measured tokens/s
                # slack ranks ahead of the free-block proxy (members
                # without a capacity block score 0 slack and fall
                # through to the proxy unchanged).
                target = min(
                    candidates,
                    key=lambda m: (adapter is not None
                                   and adapter not in m.adapters,
                                   sticky is not None
                                   and m.id != sticky,
                                   self._assigned(m.id),
                                   -self._headroom(m),
                                   -self._blocks_free(m), m.id),
                )
            else:
                target = min(
                    candidates,
                    key=lambda m: (adapter is not None
                                   and adapter not in m.adapters,
                                   sticky is not None
                                   and m.id != sticky,
                                   self._assigned(m.id),
                                   -self._blocks_free(m), m.id),
                )
            if sticky is not None and target.id == sticky:
                self.counters["prefix_affinity_hits"] += 1
        if pkey is not None:
            self._note_prefix_sticky(pkey, target.id)
        track.replica = target.id
        workers = [w for w in self._workers.values()
                   if w.alive and w.inbox is not None]
        if adapter is not None:
            # A tenant's prompt must be prefilled THROUGH its adapter —
            # a pool-less worker would hand off base-model KV, and a
            # pool-capable worker the router cannot hot-load (tenant
            # loaded member-side only, never registered here) must
            # already HOLD the factors.  No usable worker = direct
            # submission (the replica prefills through its own pool).
            workers = [w for w in workers
                       if w.caps.get("max_adapters", 0) > 0
                       and (adapter in self._adapters
                            or adapter in w.adapters)]
        if adapter is not None:
            # The decode replica needs the factors resident whichever
            # path the prompt takes (handoff admission decodes through
            # them).  A dead replica outbox here is a REPLICA incident:
            # its death path re-routes this rid (track.replica is set).
            try:
                self._ensure_adapter(target, adapter)
            except (OSError, ConnectionError):
                self._on_replica_death(target, now)
                return
        if workers:
            worker = min(workers,
                         key=lambda w: (adapter is not None
                                        and adapter not in w.adapters,
                                        self._pending(w.id), w.id))
            try:
                if adapter is not None:
                    self._ensure_adapter(worker, adapter)
                # tmpfs zero-copy only when the worker and the replica
                # advertise the same host; otherwise the payload rides
                # inline bytes over the (chunk-sending) queue.
                self._put(worker.inbox, make_dispatch_item(
                    req, target.inbox,
                    same_host=worker.inbox[0] == target.inbox[0]),
                    on_sent=self._placement_cb(track, rid, worker.id,
                                               target.id))
                track.worker = worker.id
                self.counters["prefill_dispatches"] += 1
                return
            except (OSError, ConnectionError):
                self._on_worker_death(worker, now)
                # fall through to direct submission this once
        try:
            self._put(target.inbox, req,
                      on_sent=self._placement_cb(track, rid, None,
                                                 target.id))
            self.counters["direct_submits"] += 1
        except (OSError, ConnectionError):
            self._on_replica_death(target, now)

    def _ensure_adapter(self, m: _Member,
                        name: str) -> None:  # rlt: holds self._lock
        """Hot-load ``name`` onto ``m`` unless it already holds it: a
        ``serve_adapter_load`` frame down the member's ordered inbox
        lane, so the factors always land BEFORE the dispatch that
        references them.  The optimistic set-add keeps one tenant's
        burst from re-shipping the blob every placement; the member's
        next beat is the correcting truth."""
        if name in m.adapters:
            return
        from ray_lightning_tpu.serve.dist.handoff import (
            make_adapter_load_item,
        )

        entry = self._adapters.get(name)
        if entry is None:
            # Beat-advertised-only tenant (loaded member-side, never
            # registered with the router) placed on a non-holder —
            # placement filters should prevent this; if one slips
            # through, the member's own typed "unknown adapter" reply
            # is the failure surface, not a router crash.
            log.warning(
                "no registered blob to hot-load adapter %r onto %s %s",
                name, m.role, m.id,
            )
            return
        self._put(m.inbox, make_adapter_load_item(
            name, entry["rank"], data=entry["data"],
        ))
        m.adapters.add(name)
        self.counters["adapter_loads_sent"] += 1

    def _placement_cb(self, track: _Track, rid: str,
                      worker_id: Optional[str], replica_id: str):
        """The ``placement`` span recorder, fired by the outbox thread
        AFTER the dispatch frame hit the wire — so the span measures
        route decision + outbox queue + connect + send, the real
        dispatch latency a client's TTFT pays."""
        if not self.tracer.enabled or track.trace is None:
            return None
        t0 = time.time()
        ctx = child_context(track.trace)
        resubmit = track.resubmits

        def on_sent(_enqueue_ts: float) -> None:
            args = trace_args(ctx, rid=rid, replica=replica_id,
                              resubmit=resubmit)
            if worker_id is not None:
                args["worker"] = worker_id
            self.tracer.record(
                "placement", t0, max(0.0, time.time() - t0), args=args
            )

        return on_sent

    def _park(self, rid: str) -> None:  # rlt: holds self._lock
        if rid not in self._retry:
            self._retry.append(rid)

    # rlt: holds self._lock
    def _finish_unroutable(self, rid: str, track: _Track, status: str,
                           error: str) -> None:
        self._inflight.pop(rid, None)
        self.counters["invalid" if status == "invalid" else "rejected"] \
            += 1
        done: Dict[str, Any] = {
            "type": "serve_done", "rid": rid, "status": status,
            "tokens": [],
        }
        if status == "rejected":
            done["reason"] = "rejected"
        else:
            done["error"] = error
        self._reply(tuple(track.req["reply"]), done)

    def _drain_retry(self, now: float) -> None:  # rlt: holds self._lock
        pending, self._retry = list(self._retry), deque()
        for rid in pending:
            track = self._inflight.get(rid)
            if track is None:
                continue
            track.replica = None
            self._route(rid, track, now, must_place=True)

    # -- liveness / failover -------------------------------------------------
    def _check_liveness(self, now: float) -> None:  # rlt: holds self._lock
        for m in list(self._replicas.values()):
            if m.alive and self._is_lost(m, now):
                self._on_replica_death(m, now)
        for w in list(self._workers.values()):
            if w.alive and self._is_lost(w, now):
                self._on_worker_death(w, now)

    def _is_lost(self, m: _Member, now: float) -> bool:
        try:
            if not m.handle.is_alive():
                return True
        except Exception:  # noqa: BLE001 - a broken handle IS dead
            return True
        if now < m.migrating_until:
            # A drain's migration-export claim is in flight: the
            # device->host KV gather can silence beats for longer than
            # lost_after_s, and declaring the exporter dead here would
            # race a recompute failover against migration frames
            # already on the wire for the SAME rids — double-placing
            # every resident request.  The claim is bounded
            # (migration_claim_s): a replica that dies mid-export just
            # fails over a little later, and loses nothing the crash
            # path wouldn't have lost anyway.
            return False
        grace = self.lost_after_s if m.last_beat is not None \
            else self.hello_grace_s
        return m.beat_age_s(now) > grace

    def _on_replica_death(self, m: _Member,
                          now: float) -> None:  # rlt: holds self._lock
        """Serving-side fault tolerance: fail the dead replica's
        in-flight requests over to survivors.  Re-submission rides the
        engines' recompute-preemption path — tokens re-emit from index
        0 with the SAME router-stamped sample seed, clients dedup on
        index, so the stream is bitwise-continuous and nothing is
        lost."""
        if not m.alive:
            return
        m.alive = False
        self.counters["replica_deaths"] += 1
        self.last_failover_detect_s = m.beat_age_s(now)
        victims = [rid for rid, t in self._inflight.items()
                   if t.replica == m.id]
        log.warning(
            "serve replica %s lost (last beat %.1fs ago) — failing over "
            "%d in-flight request(s)", m.id, m.beat_age_s(now),
            len(victims),
        )
        if victims:
            self.counters["failovers"] += 1
            self.counters["failed_over_requests"] += len(victims)
        for rid in victims:
            track = self._inflight[rid]
            if track.hedge_replica is not None \
                    and track.hedge_replica != m.id:
                # Hot-spare promotion: the hedge placement is already
                # decoding this rid with the same seed — no recompute
                # failover needed, just retarget the track.
                track.replica, track.hedge_replica = \
                    track.hedge_replica, None
                continue
            track.replica = None
            track.worker = None
            track.hedge_replica = None
            track.resubmits += 1
            if track.trace is not None:
                # The failover hop is a first-class span LINKED under
                # the request root: anyone reading the stitched trace
                # sees that this request moved replicas, and why.
                self.tracer.record(
                    "failover", time.time(), 0.0,
                    args=trace_args(
                        child_context(track.trace), rid=rid,
                        from_replica=m.id, reason="replica_lost",
                        resubmit=track.resubmits,
                    ),
                )
            self._route(rid, track, now, exclude={m.id}, must_place=True)
        for t in self._inflight.values():
            if t.hedge_replica == m.id:
                t.hedge_replica = None  # primary placement still live
        self._reap(m)

    def _on_worker_death(self, w: _Member,
                         now: float) -> None:  # rlt: holds self._lock
        if not w.alive:
            return
        w.alive = False
        self.counters["worker_deaths"] += 1
        pending = [rid for rid, t in self._inflight.items()
                   if t.worker == w.id]
        log.warning(
            "serve prefill worker %s lost — re-dispatching %d pending "
            "prompt(s)", w.id, len(pending),
        )
        for rid in pending:
            track = self._inflight[rid]
            track.worker = None
            track.resubmits += 1
            self._route(rid, track, now, must_place=True)
        if self._prefill_factory is not None:
            if self.governor.permit(now):
                try:
                    self.add_prefill(self._prefill_factory())
                    self.counters["prefill_respawns"] += 1
                except Exception:  # noqa: BLE001 - a failed respawn
                    # must not take the router down; the governor slot
                    # is burnt either way (that is the point)
                    log.warning("prefill respawn failed", exc_info=True)
            else:
                self.counters["prefill_respawns_denied"] += 1
                log.warning(
                    "prefill worker %s NOT respawned: restart window "
                    "exhausted (%d per %.0fs)", w.id,
                    self.governor.max_restarts, self.governor.window_s,
                )
        self._reap(w)

    def _reap(self, m: _Member) -> None:
        """Best-effort corpse cleanup OFF the control-plane thread: the
        member is already marked dead and unrouted, and ``kill()`` on a
        false-positive death (process alive, beats merely stalled) can
        block tens of seconds in a drain/join — under the router lock
        that would freeze every client of the fleet."""
        def kill_quietly():
            try:
                m.handle.kill()
            except Exception:  # noqa: BLE001 - reaping is best-effort,
                # but a swallowed kill failure (RLT007) would hide a
                # leaked member process from the operator entirely.
                log.debug("reap of %s %s failed", m.role, m.id,
                          exc_info=True)

        threading.Thread(target=kill_quietly, name="rlt-router-reap",
                         daemon=True).start()
        self._sweep_segments()

    def _sweep_segments(self) -> None:
        """Dead prefill handoffs (producer pid gone, never consumed)
        must not leak tmpfs — mirrored by ``ServeEngine.stop``."""
        try:
            from ray_lightning_tpu.cluster.shm import sweep_stale_segments

            sweep_stale_segments("rlt-kv")
        except Exception:  # noqa: BLE001 - janitorial, never raises out
            pass

    # -- wire helpers --------------------------------------------------------
    def _outbox(self,
                addr: Tuple[str, int]) -> MemberOutbox:  # rlt: holds self._lock
        if self._closing:
            raise ConnectionError("router is stopping")
        addr = (addr[0], int(addr[1]))
        box = self._outboxes.get(addr)
        if box is None or box._dead:
            if box is not None:
                box.close(drain_s=0.0)
            # The error callback is bound to the BOX identity (late,
            # below) — a stale failure report must never tear down a
            # healthy replacement lane at the same address.
            box = MemberOutbox(addr)
            box._on_error = (
                lambda e, b=box: self._on_outbox_error(b, e)
            )
            self._outboxes[addr] = box
        return box

    def _reap_idle_outboxes(self, now: float) -> None:
        """Close send lanes that have been idle past the threshold —
        one thread + socket per DISTINCT client reply address must not
        accumulate over a long-lived router's lifetime.  Victims are
        collected under the lock but closed outside it (close joins
        the lane thread)."""
        with self._lock:
            victims = [
                addr for addr, box in self._outboxes.items()
                if not box.pending
                and now - box.last_used > self._outbox_idle_s
            ]
            boxes = [self._outboxes.pop(a) for a in victims]
        for box in boxes:
            box.close(drain_s=0.0)

    def _put(self, addr: Tuple[str, int], item: Dict[str, Any],
             on_sent=None) -> None:
        self._outbox(addr).put(item, on_sent=on_sent)

    def flush_outboxes(self, timeout: float = 5.0) -> bool:
        """Wait until every live outbox has drained to the wire (tests
        and planned teardowns want the async sends LANDED, not merely
        enqueued).  Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                busy = any(box.pending and not box._dead
                           for box in self._outboxes.values())
            if not busy:
                return True
            time.sleep(0.005)
        return False

    def _on_outbox_error(self, failed_box: MemberOutbox,
                         exc: BaseException) -> None:
        """An async send failed (reported by the outbox thread).  Map
        the address back to whichever member currently advertises it
        and run the SAME death path a synchronous send failure used to
        take; a client reply address just drops its outbox (the client
        went away).  Only the FAILED box is unregistered — a healthy
        replacement lane already installed at the same address (a _put
        raced this callback) keeps its queued frames."""
        now = time.monotonic()
        addr = failed_box.addr
        victim = None
        with self._lock:
            if self._outboxes.get(addr) is failed_box:
                self._outboxes.pop(addr, None)
            for m in list(self._replicas.values()):
                if m.alive and m.inbox == addr:
                    victim = m
                    break
            else:
                for w in list(self._workers.values()):
                    if w.alive and w.inbox == addr:
                        victim = w
                        break
        failed_box.close(drain_s=0.0)  # self-join-safe (dead: no join)
        if victim is not None:
            log.warning("outbox to %s %s failed: %r", victim.role,
                        victim.id, exc)
            with self._lock:
                if victim.role == "decode":
                    self._on_replica_death(victim, now)
                else:
                    self._on_worker_death(victim, now)

    def _reply(self, addr: Tuple[str, int], item: Dict[str, Any]) -> None:
        try:
            self._put(addr, item)
        except (OSError, ConnectionError):
            pass  # client went away; nothing to tell it

    # -- telemetry -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The router's live snapshot (schema:
        ``telemetry/schema.py::validate_router_snapshot``)."""
        now = time.monotonic()
        with self._lock:
            replicas = []
            cap_blocks = []
            for m in self._replicas.values():
                gauges = (m.snapshot.get("gauges", {})
                          if m.snapshot else {})
                entry: Dict[str, Any] = {
                    "id": m.id,
                    "alive": bool(m.alive),
                    "inflight": self._assigned(m.id),
                    "last_beat_age_s": (
                        round(now - m.last_beat, 3)
                        if m.last_beat is not None else None
                    ),
                }
                for key in ("slots_active", "num_slots", "queue_depth",
                            "blocks_free", "num_blocks",
                            "spec_acceptance_rate",
                            "prefix_cache_hit_rate"):
                    if key in gauges:
                        entry[key] = float(gauges[key])
                if m.recompiles is not None:
                    entry["recompiles"] = m.recompiles
                if m.caps.get("max_adapters", 0) > 0:
                    entry["adapters"] = len(m.adapters)
                cap = (m.snapshot.get("capacity")
                       if m.snapshot else None)
                if isinstance(cap, dict):
                    cap_blocks.append(cap)
                    for key in ("headroom_tokens_per_s",
                                "utilization", "kv_exhaustion_eta_s"):
                        if key in cap:
                            entry[key] = cap[key]
                replicas.append(entry)
            workers = []
            for w in self._workers.values():
                wentry: Dict[str, Any] = {
                    "id": w.id,
                    "alive": bool(w.alive),
                    "pending": self._pending(w.id),
                    "last_beat_age_s": (
                        round(now - w.last_beat, 3)
                        if w.last_beat is not None else None
                    ),
                }
                if w.caps.get("max_adapters", 0) > 0:
                    wentry["adapters"] = len(w.adapters)
                workers.append(wentry)
            out = {
                "ts": time.time(),
                "counters": dict(self.counters),
                "replicas": replicas,
                "workers": workers,
            }
            if self.brownout is not None:
                out["brownout_level"] = int(self.brownout.level)
            if cap_blocks:
                from ray_lightning_tpu.serve.capacity import (
                    aggregate_fleet,
                )

                fleet = aggregate_fleet(cap_blocks)
                if fleet is not None:
                    out["capacity"] = fleet
            return out

    def _maybe_export(self) -> None:
        if self._exporter is None and self._live_path is None:
            return
        now = time.monotonic()
        if now - self._last_export < self._export_every_s:
            return
        self._last_export = now
        snap = self.snapshot()
        if self._exporter is not None:
            self._exporter.update({"router": snap})
        if self._live_path is not None:
            import json
            import os

            tmp = self._live_path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"ts": snap["ts"], "router": snap}, f)
                os.replace(tmp, self._live_path)
            except OSError:
                pass  # a full disk must not take the router down
