"""Disaggregated multi-replica serving (ISSUE 12).

The distributed half of the serving plane: dedicated **prefill
workers** run prompts on their own devices and ship the resulting
paged-KV blocks over the queue plane (``SegmentStore`` zero-copy
same-host, chunked ``QueueHandle`` frames cross-host) to **decode
replicas** — N independent engines — behind one load-aware **router**
with per-replica admission, heartbeat-based failover (dead replica →
in-flight requests recompute on survivors, streams bitwise-continuous
via the router-stamped sampling seeds + token-index dedup) and a
sliding-window restart governor for prefill workers.

* :mod:`.handoff` — the wire frames (dispatch / KV handoff / adapter
  hot-load / hello / beat; envelopes schema-pinned in
  ``telemetry/schema.py``);
* :mod:`.prefill` — the prefill worker loop (prefill → export →
  handoff);
* :mod:`.replica` — decode-replica runners, in-process and
  ProcessActor deployment shapes, fleet builders;
* :mod:`.router` — placement, admission, fault tolerance, the
  ``router-live.json`` / per-replica OpenMetrics export.

See docs/SERVING.md "Disaggregated serving" for the dataflow diagram,
wire format and failover semantics; ``bench_serve.py`` carries the
disagg-vs-monolith A/B and the kill-a-replica chaos arm.
"""

from ray_lightning_tpu.serve.dist.handoff import (
    KV_SEGMENT_PREFIX,
    make_adapter_load_item,
    make_beat_item,
    make_dispatch_item,
    make_handoff_item,
    make_hello_item,
    request_fields,
)
from ray_lightning_tpu.serve.dist.prefill import PrefillRunner
from ray_lightning_tpu.serve.dist.replica import (
    ActorPrefill,
    ActorReplica,
    DecodeReplicaRunner,
    InprocPrefill,
    InprocReplica,
    ServeFleet,
    launch_actor_fleet,
    launch_inproc_fleet,
)
from ray_lightning_tpu.serve.dist.router import RestartGovernor, Router

__all__ = [
    "Router",
    "RestartGovernor",
    "ServeFleet",
    "launch_inproc_fleet",
    "launch_actor_fleet",
    "PrefillRunner",
    "DecodeReplicaRunner",
    "InprocReplica",
    "InprocPrefill",
    "ActorReplica",
    "ActorPrefill",
    "KV_SEGMENT_PREFIX",
    "request_fields",
    "make_dispatch_item",
    "make_handoff_item",
    "make_adapter_load_item",
    "make_hello_item",
    "make_beat_item",
]
