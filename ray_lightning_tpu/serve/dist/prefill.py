"""Prefill workers: dedicated prompt capacity for the disaggregated
serving plane.

Long prompts are the serving plane's head-of-line blocker: a monolith
engine interleaves bucketed prefill dispatches with the fixed-width
decode tick, so every admission stalls every in-flight token stream
for one trunk forward.  A prefill worker moves that work onto its OWN
device (its own mesh/params): it runs the SAME ``paged_prefill``
program the engine would, exports the resulting per-layer KV blocks to
host (``PagedKVCache.export_blocks``), and ships them — plus the
final-position logits — to the chosen decode replica's inbox as a
``serve_kv_handoff`` frame.  The replica scatters them into its own
free blocks and goes straight to decode: decode ticks never pay for
prompts again.

Transport mirrors the MPMD lane: same-host payloads ride
``SegmentStore`` tmpfs segments (prefix ``rlt-kv``; the consuming
replica unlinks on read), cross-host payloads ride inline bytes
through the chunk-sending ``QueueHandle``.  Unconsumed segments (a
replica died between handoff and read) are TTL-pruned here, swept by
pid at every teardown (engine close, router failover, actor kill).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_lightning_tpu.fault.inject import (
    FaultBlackhole, fire as _fault_fire, set_member,
)
from ray_lightning_tpu.serve.dist.handoff import (
    KV_SEGMENT_PREFIX, CachedSender, encode_kv_payload, make_beat_item,
    make_handoff_item, make_hello_item,
)

__all__ = ["PrefillRunner"]

log = logging.getLogger(__name__)

# Same-host handoffs above this ride tmpfs segments (the MPMD lane's
# threshold — kernel socket buffers both ways vs one tmpfs write).
_SHM_THRESHOLD_BYTES = 256 << 10
# Unconsumed segments older than this are presumed addressed to a dead
# replica and unlinked (consumed ones are already gone — the replica
# unlinks on read, so this unlink is an ENOENT no-op for them).
_SEGMENT_TTL_S = 120.0


class PrefillRunner:
    """One prefill worker: inbox + compiled prefill programs + the
    handoff send path.  Transport/process-agnostic — drive it on a
    thread in the driver process (tests, the example) or inside a
    :class:`~ray_lightning_tpu.cluster.actor.ProcessActor`
    (``replica.py::run_prefill_worker``)."""

    def __init__(self, worker_id: str, module, params, serve_cfg,
                 beat_handle, *, beat_s: float = 0.25,
                 shm_threshold: int = _SHM_THRESHOLD_BYTES,
                 segment_ttl_s: float = _SEGMENT_TTL_S,
                 trace_dir: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.cluster.queue import DriverQueue
        from ray_lightning_tpu.models.generate import _reject_unmerged_lora
        from ray_lightning_tpu.serve.kv_cache import (
            PagedKVCache, PrefixIndex, paged_prefill, paged_verify_step,
        )
        from ray_lightning_tpu.serve.scheduler import derive_geometry

        self.worker_id = worker_id
        self.module = module
        self.cfg = module.config
        self.serve_cfg = serve_cfg
        _reject_unmerged_lora(params)
        self.params = jax.tree.map(jnp.asarray, params)
        self._c = module._compute_dtype()
        self.max_model_len, self.buckets = derive_geometry(
            serve_cfg, self.cfg
        )
        # The worker's pool only ever holds ONE in-flight prompt (the
        # dispatch loop is sequential): the largest bucket's blocks
        # plus the reserved trash block.  With the prefix cache on, the
        # pool also hosts RESIDENT chains between dispatches, so it is
        # sized like an engine pool (cfg.num_blocks, or a few buckets'
        # worth) — eviction, not sizing, handles the pressure.
        blocks_per_bucket = self.buckets[-1] // serve_cfg.block_size
        pool_blocks = blocks_per_bucket + 1
        if getattr(serve_cfg, "prefix_cache", False):
            pool_blocks = max(
                pool_blocks,
                getattr(serve_cfg, "num_blocks", None)
                or 4 * blocks_per_bucket + 1,
            )
        self.cache = PagedKVCache(
            self.cfg, pool_blocks, serve_cfg.block_size, dtype=self._c,
        )
        self._pool = self.cache.init_pool()
        cfg, c = self.cfg, self._c
        # Multi-tenant LoRA: the worker mirrors the decode replicas'
        # adapter pool (serve/lora.py) — a tenant's prompt must be
        # prefilled THROUGH its adapter or the handed-off KV would be
        # the base model's.  The router hot-loads adapters here over
        # the same serve_adapter_load frames replicas get.
        self.adapters = None
        if getattr(serve_cfg, "max_adapters", 0) > 0:
            from ray_lightning_tpu.serve.lora import AdapterPool

            self.adapters = AdapterPool(
                self.cfg, serve_cfg.max_adapters,
                serve_cfg.adapter_rank, dtype=self._c,
            )
        lora_impl = self.adapters.impl if self.adapters is not None \
            else "xla"

        def _prefill(params, pool, tokens, prompt_len, block_ids,
                     ad, ad_id):
            return paged_prefill(cfg, params, pool, tokens, prompt_len,
                                 block_ids, compute_dtype=c,
                                 adapters=ad, adapter_id=ad_id,
                                 lora_impl=lora_impl)

        from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit

        # One executable per bucket length, like the engine's set.
        self._prefill_fn = ledgered_jit(_prefill, site="serve/dist_prefill")

        def _suffix(params, pool, table_row, start, tokens, limit,
                    sample_idx, ad, ad_ids):
            # Suffix-only prefill over claimed prefix blocks: the
            # engine's chunk program minus the sampling tail (a prefill
            # WORKER ships final-position logits, it never samples —
            # the consuming replica's _first program does, bitwise the
            # local path).  Window writes land at start + [0, T); the
            # claimed frontier sits strictly below start, so resident
            # chain blocks are read-only here.
            logits, pool = paged_verify_step(
                cfg, params, pool, table_row, start, tokens, limit,
                compute_dtype=c, adapters=ad, adapter_ids=ad_ids,
                lora_impl=lora_impl,
            )
            pick = jax.lax.dynamic_index_in_dim(
                logits[0], sample_idx, axis=0, keepdims=False
            )
            return pick, pool

        # One executable per suffix bucket width (the same bounded set
        # the bucketed prefill compiles over).
        self._suffix_fn = ledgered_jit(_suffix, site="serve/dist_suffix")
        # Prefix-aware KV reuse on the worker: a dispatch whose prompt
        # shares a resident whole-block prefix claims those blocks by
        # refcount and computes ONLY the suffix — the export still
        # covers the full bucket, so the handoff wire format (and the
        # consuming replica) are unchanged.
        self.prefix: Optional[PrefixIndex] = None
        if getattr(serve_cfg, "prefix_cache", False):
            self.prefix = PrefixIndex(
                self.cache.allocator, serve_cfg.block_size
            )
        self._inbox = DriverQueue()
        self._beat_handle = beat_handle
        self.beat_s = beat_s
        self._shm_threshold = shm_threshold
        self._segment_ttl_s = segment_ttl_s
        self._store = None           # SegmentStore, lazily created
        self._out = CachedSender()
        # Work thread appends, beat thread prunes/drains: everything
        # below is shared between them (the PR-12 review races).
        self._feed_lock = threading.Lock()
        # guarded by self._feed_lock
        self._live_segments: List[Tuple[str, float]] = []
        self._done: List[Tuple[str, str]] = []    # guarded by self._feed_lock
        self._failed: List[Tuple[str, str]] = []  # guarded by self._feed_lock
        self._last_beat = 0.0
        self.prefills = 0
        self.suffix_prefills = 0  # dispatches served over a claimed prefix
        # Distributed tracing: worker-side spans continue the router-
        # stamped request context (SpanTracer.start_remote), exported
        # at close for trace_collect.py to stitch.
        from ray_lightning_tpu.telemetry.spans import SpanTracer

        self._trace_dir = trace_dir
        self.tracer = SpanTracer(
            enabled=trace_dir is not None, maxlen=16384, rank=0,
            clock=time.time,
        )
        # Hard-kill simulation (InprocPrefill.kill(hard=True)): a dead
        # process sends no final beat — suppress the closing flag so
        # the router takes the death path, not the planned-drain one.
        self.suppress_final = False

    @property
    def handle(self):
        return self._inbox.handle

    def hello(self) -> None:
        """Register with the router: inbox address + the geometry caps
        placement and validation run on."""
        self._beat_handle.put(make_hello_item(
            "prefill", self.worker_id,
            (self._inbox.handle.host, self._inbox.handle.port),
            max_prompt_len=self.buckets[-1],
            max_model_len=self.max_model_len,
            block_size=self.serve_cfg.block_size,
            max_adapters=getattr(self.serve_cfg, "max_adapters", 0),
        ))

    # -- the loop ------------------------------------------------------------
    def step(self, timeout: float = 0.1) -> bool:
        """Process at most one dispatch; returns True when one was."""
        import queue as _pyqueue

        try:
            item = self._inbox.get(timeout=timeout)
        except _pyqueue.Empty:
            return False
        try:
            self._process(item)
        except Exception as e:  # noqa: BLE001 - a bad dispatch must
            # surface as a failed rid the router re-routes, never kill
            # the worker loop
            rid = item.get("rid") if isinstance(item, dict) else None
            log.warning("prefill %s: dispatch failed: %s",
                        self.worker_id, e, exc_info=True)
            if rid is not None:
                with self._feed_lock:
                    self._failed.append((str(rid), repr(e)))
        return True

    def run(self, stop=None) -> None:
        """Serve dispatches until ``stop()`` goes true (a
        ``threading.Event.is_set`` inproc, the fault plane's
        ``drain_requested`` inside an actor).

        Beats ride their OWN thread, so they keep flowing while the
        work loop sits inside a multi-second prefill compile — the same
        asymmetry the training monitor's heartbeat publisher relies on.
        A beat-starved worker would be declared lost and its dispatches
        redundantly re-routed on its very first compile."""
        set_member("prefill", self.worker_id)
        self.hello()
        done = threading.Event()

        def beat_loop():
            # Member identity is thread-local: the beat thread declares
            # its own so worker:-pinned beat faults fire here too.
            set_member("prefill", self.worker_id)
            while not done.is_set():
                self._maybe_beat()
                done.wait(min(self.beat_s, 0.1))

        beater = threading.Thread(
            target=beat_loop, name=f"rlt-prefill-beat-{self.worker_id}",
            daemon=True,
        )
        beater.start()
        try:
            while not (stop() if stop is not None else False):
                self.step(timeout=min(self.beat_s, 0.1))
        finally:
            done.set()
            beater.join(timeout=10)
            if not self.suppress_final:
                try:
                    # Final done/failed feed, flagged as a PLANNED
                    # drain — without `closing` the router would read
                    # this scale-down as a death: failure counters, a
                    # burnt respawn-governor slot, and a replacement
                    # worker the operator just tried to remove.
                    self._maybe_beat(force=True, closing=True)
                except Exception:  # noqa: BLE001 - router may be gone
                    pass
            self.close()

    def _process(self, item: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        import numpy as np

        if isinstance(item, dict) \
                and item.get("type") == "serve_adapter_load":
            # Tenant hot-load: the router ensures the load frame lands
            # BEFORE any of the tenant's dispatches (one ordered inbox
            # lane per member), so resolution below never races it.
            from ray_lightning_tpu.serve.lora import decode_adapter

            if self.adapters is None:
                raise ValueError(
                    "serve_adapter_load on a prefill worker without an "
                    "adapter pool (serve_cfg.max_adapters == 0)"
                )
            _fault_fire("adapter_load", rid=str(item.get("name", "")))
            name = str(item["name"])
            if self.prefix is not None:
                # A hot-(re)load may replace the adapter's weights:
                # chains prefilled through the old weights are stale.
                # _process runs only on the work thread, so the drop
                # needs no deferral (unlike the engine's step-drained
                # queue).
                self.prefix.drop(name)
            self.adapters.add(name, decode_adapter(item))
            return
        if not (isinstance(item, dict)
                and item.get("type") == "serve_prefill_dispatch"):
            raise ValueError(
                f"unexpected item on prefill inbox: {type(item).__name__}"
            )
        req = item["req"]
        rid = str(req["rid"])
        adapter = req.get("adapter")
        ad, ad_id = None, None
        if self.adapters is not None:
            ad = self.adapters.buffers
            # Unknown tenant raises → the failed feed → router
            # re-routes (and re-ensures the load) — never a silent
            # base-model prefill for a tenant's prompt.
            ad_id = np.int32(0 if adapter is None
                             else self.adapters.slot_of(adapter))
        elif adapter is not None:
            raise ValueError(
                f"dispatch names adapter {adapter!r} but this worker "
                f"has no adapter pool"
            )
        prompt = [int(t) for t in req["prompt"]]
        bucket = next(b for b in self.buckets if b >= len(prompt))
        n_blocks = bucket // self.serve_cfg.block_size
        claimed: List[int] = []
        if self.prefix is not None:
            # Same cap as the engine's claim hook: the FINAL prompt
            # token's block is always computed here — its forward
            # produces the logits the handoff ships.
            cap = (len(prompt) - 1) // self.serve_cfg.block_size
            claimed = self.prefix.claim(adapter, prompt, cap)
        start = len(claimed) * self.serve_cfg.block_size
        ids = self.cache.allocator.alloc(n_blocks - len(claimed))
        if ids is None and self.prefix is not None:
            # Cache pressure: shed cold chains first, then (if this
            # very claim pins too much) fall back to a full recompute
            # with the cache flushed — never fail the dispatch.
            self.prefix.evict(n_blocks - len(claimed))
            ids = self.cache.allocator.alloc(n_blocks - len(claimed))
            if ids is None:
                if claimed:
                    self.cache.allocator.free(claimed)
                    claimed, start = [], 0
                self.prefix.evict(n_blocks)
                ids = self.cache.allocator.alloc(n_blocks)
            if ids is None:
                self.prefix.drop_all()
                ids = self.cache.allocator.alloc(n_blocks)
        assert ids is not None, "worker pool sized for the largest bucket"
        ids = list(claimed) + list(ids)
        req_ctx = None
        if self.tracer.enabled:
            from ray_lightning_tpu.telemetry.propagate import extract

            req_ctx = extract(req)  # the router-stamped trace root
        with self.tracer.start_remote(
                req_ctx, "prefill_compute", rid=rid,
                worker=self.worker_id, bucket=bucket) as pf_span:
            ok = False
            try:
                if start == 0:
                    padded = np.zeros((bucket,), np.int32)
                    padded[: len(prompt)] = prompt
                    logits, self._pool = self._prefill_fn(
                        self.params, self._pool, jnp.asarray(padded),
                        np.int32(len(prompt)),
                        jnp.asarray(np.asarray(ids, np.int32)),
                        ad, ad_id,
                    )
                else:
                    # Shared prefix resident: compute ONLY the suffix.
                    suffix = len(prompt) - start
                    width = next(b for b in self.buckets if b >= suffix)
                    window = np.zeros((1, width), np.int32)
                    window[0, :suffix] = prompt[start:]
                    row = np.zeros(
                        (1, self.buckets[-1]
                         // self.serve_cfg.block_size), np.int32,
                    )  # TRASH-padded past the prompt's blocks
                    row[0, : len(ids)] = ids
                    ad_ids = None if ad is None else jnp.asarray(
                        [int(ad_id)], jnp.int32
                    )
                    logits, self._pool = self._suffix_fn(
                        self.params, self._pool, jnp.asarray(row),
                        jnp.asarray(np.full((1,), start, np.int32)),
                        jnp.asarray(window),
                        jnp.asarray(np.full((1,), len(prompt),
                                            np.int32)),
                        np.int32(suffix - 1), ad, ad_ids,
                    )
                    self.suffix_prefills += 1
                # export_blocks device_gets the blocks, so the span
                # closes on a SYNCED device — real prefill compute.
                kv = self.cache.export_blocks(self._pool, ids)
                ok = True
            finally:
                if ok and self.prefix is not None:
                    # Publish the whole-block prompt prefix; the index
                    # retains the chain, so the free below only drops
                    # THIS dispatch's handles and resident blocks
                    # survive for the next sharing prompt to claim.
                    n_full = len(prompt) // self.serve_cfg.block_size
                    if n_full:
                        self.prefix.insert(
                            adapter, prompt, ids[:n_full]
                        )
                self.cache.allocator.free(ids)
        with self.tracer.start_remote(
                pf_span.ctx, "handoff_send", rid=rid) as send_span:
            payload = encode_kv_payload(kv, np.asarray(logits))
            # The envelope carries the WORKER's span + send timestamp:
            # the consuming replica books handoff_transfer from it and
            # its admission spans parent under this worker's spans.
            handoff_trace = send_span.ctx or pf_span.ctx
            shm_path = None
            if item.get("same_host", False) \
                    and len(payload) >= self._shm_threshold:
                shm_path = self._segment_store().put(payload)
                with self._feed_lock:  # beat thread prunes concurrently
                    self._live_segments.append((shm_path,
                                                time.monotonic()))
                out = make_handoff_item(req, bucket, shm=shm_path,
                                        trace=handoff_trace)
            else:
                out = make_handoff_item(req, bucket, data=payload,
                                        trace=handoff_trace)
        try:
            # Serve fault grammar: shm_vanish unlinks the segment here
            # (the consumer's read then fails retryably), torn corrupts
            # it, blackhole drops the frame below.
            _fault_fire("handoff_send", rid=rid, path=shm_path)
        except FaultBlackhole:
            # Injected partition: the frame is "sent" but never
            # arrives.  An shm segment ages out via the TTL janitor,
            # exactly like a real replica death between send and read;
            # recovery is client/router-driven (deadline + retry).
            return
        try:
            self._put(tuple(item["kv_to"]), out)
        except (OSError, ConnectionError) as e:
            # The replica's inbox is unreachable (dying or dead): give
            # the segment back ourselves (no consumer will unlink it)
            # and report the rid so the router re-routes.
            if shm_path is not None:
                self._unlink(shm_path)
            with self._feed_lock:
                self._failed.append((rid, repr(e)))
            return
        self.prefills += 1
        with self._feed_lock:
            self._done.append((rid, "handoff"))

    # -- transport helpers ---------------------------------------------------
    def _segment_store(self):
        if self._store is None:
            from ray_lightning_tpu.cluster.shm import SegmentStore

            self._store = SegmentStore(prefix=KV_SEGMENT_PREFIX)
        return self._store

    def _put(self, addr: Tuple[str, int], item: Dict[str, Any]) -> None:
        self._out.put(addr, item)

    @staticmethod
    def _unlink(path: str) -> None:
        import os

        try:
            os.unlink(path)
        except OSError:
            pass

    def _prune_segments(self, now: float) -> None:
        """TTL janitor for handoffs whose replica died between send and
        read — the pid-based sweep cannot collect them (this producer
        is alive); the TTL can."""
        with self._feed_lock:  # work thread appends concurrently
            expired = [p for p, t in self._live_segments
                       if now - t > self._segment_ttl_s]
            self._live_segments = [
                (p, t) for p, t in self._live_segments
                if now - t <= self._segment_ttl_s
            ]
        for path in expired:
            self._unlink(path)

    def _maybe_beat(self, force: bool = False,
                    closing: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_beat < self.beat_s:
            return
        self._last_beat = now
        self._prune_segments(now)
        try:
            # Before the feed drain: a blackholed beat loses nothing —
            # the next beat carries the same done/failed entries.
            _fault_fire("beat")
        except FaultBlackhole:
            return
        with self._feed_lock:
            done, self._done = self._done, []
            failed, self._failed = self._failed, []
        try:
            self._beat_handle.put(make_beat_item(
                "prefill", self.worker_id, done=done, failed=failed,
                adapters=(None if self.adapters is None
                          else self.adapters.names()),
                closing=closing,
            ))
        except (OSError, ConnectionError):
            # Router gone (shutting down); keep draining dispatches.
            with self._feed_lock:
                self._done, self._failed = done + self._done, \
                    failed + self._failed

    def close(self, consume_grace_s: float = 5.0) -> None:
        self._inbox.shutdown()
        self._out.close()
        if self.prefix is not None:
            self.prefix.drop_all()
        if self._trace_dir is not None and self.tracer.events():
            import os

            try:
                os.makedirs(self._trace_dir, exist_ok=True)
                self.tracer.export_jsonl(
                    f"{self._trace_dir}/trace-prefill-"
                    f"{self.worker_id}.jsonl"
                )
            except OSError:
                pass  # a full disk must not fail the teardown
        if self._store is None:
            return
        # A handoff already DELIVERED to a busy replica's inbox may not
        # be read yet — unlinking it now would turn an accepted request
        # into a terminal "invalid" on a planned scale-down.  The
        # consumer unlinks on read, so wait out a short grace for the
        # tracked segments to disappear before reclaiming leftovers
        # (a replica that never reads within the grace is the dead-
        # handoff case the TTL/sweep janitors exist for anyway).
        import os

        deadline = time.monotonic() + consume_grace_s
        while time.monotonic() < deadline:
            with self._feed_lock:
                paths = [p for p, _ in self._live_segments]
            if not any(os.path.exists(p) for p in paths):
                break
            time.sleep(0.05)
        self._store.unlink_all()
