"""Draft-model construction for speculative decoding.

The engine takes ANY (draft_module, draft_params) pair sharing the
target's vocabulary — a separately trained tiny model is the production
shape.  These helpers build useful pairs from a single model:

* :func:`early_exit_draft` — the draft is the target's own first
  ``n_layers`` blocks plus its embeddings/head (the "early exit" /
  layer-skip draft family): zero extra training, zero extra weights to
  ship, acceptance tracks how much of the target's prediction its
  shallow prefix already carries;
* :func:`pad_identity_layers` — the TARGET is the draft plus extra
  blocks whose residual branches are zeroed (an identity tail), so
  target logits equal draft logits exactly while the target genuinely
  pays a deeper forward.  The bench/test pair: acceptance is ~1.0 by
  construction, and perturbing the tail (``noise``) scans the
  acceptance axis without training anything.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Dict, Tuple

__all__ = ["early_exit_draft", "pad_identity_layers"]

# Block leaves whose leading axis is the layer axis (dense GPT family;
# MoE adds its own but the serving draft path is dense-only for now).
_RESIDUAL_OUT_KEYS = ("proj_w", "proj_b", "mlp_out_w", "mlp_out_b")


def early_exit_draft(module, params: Dict[str, Any],
                     n_layers: int) -> Tuple[Any, Dict[str, Any]]:
    """A draft = the target's first ``n_layers`` blocks + shared
    embeddings, final LN and (tied) head.

    The returned params ALIAS the target's arrays (sliced views of the
    stacked block leaves) — no copy of the embedding table, which is
    most of a small model's bytes.
    """
    from ray_lightning_tpu.models.gpt import GPT

    cfg = module.config
    if not 1 <= n_layers < cfg.n_layer:
        raise ValueError(
            f"early-exit draft needs 1 <= n_layers < {cfg.n_layer}, "
            f"got {n_layers}"
        )
    if cfg.n_experts > 0:
        raise ValueError("early_exit_draft supports dense GPTs only")
    draft_cfg = replace(cfg, n_layer=n_layers)
    draft = GPT(draft_cfg, attn_impl=module.attn_impl)
    draft.precision = module.precision
    draft_params = {
        **{k: v for k, v in params.items() if k != "blocks"},
        "blocks": {k: v[:n_layers] for k, v in params["blocks"].items()},
    }
    return draft, draft_params


def pad_identity_layers(module, params: Dict[str, Any], n_extra: int,
                        noise: float = 0.0,
                        seed: int = 0) -> Tuple[Any, Dict[str, Any]]:
    """A deeper target whose tail blocks are identity functions.

    Each appended block gets fresh attention/MLP weights but ZEROED
    residual-out projections (``proj_w``/``proj_b``/``mlp_out_w``/
    ``mlp_out_b``), so ``x + att(...) @ 0 + 0 == x`` — the tail
    computes full-cost attention+MLP and contributes nothing, making
    target logits bitwise-independent of the tail.  With ``noise > 0``
    the zeroed projections get ``N(0, noise)`` entries instead: the
    target drifts away from its shallow prefix and the draft acceptance
    rate falls — the knob behind the bench's acceptance-rate sweep.
    """
    import jax
    import jax.numpy as jnp

    from ray_lightning_tpu.models.gpt import GPT

    cfg = module.config
    if n_extra < 1:
        raise ValueError(f"n_extra must be >= 1, got {n_extra}")
    if cfg.n_experts > 0:
        raise ValueError("pad_identity_layers supports dense GPTs only")
    target_cfg = replace(cfg, n_layer=cfg.n_layer + n_extra)
    target = GPT(target_cfg, attn_impl=module.attn_impl)
    target.precision = module.precision
    tail = GPT(target_cfg, attn_impl=module.attn_impl).init_params(
        jax.random.PRNGKey(seed)
    )["blocks"]
    rng = jax.random.PRNGKey(seed + 1)
    blocks = {}
    for key, head_leaf in params["blocks"].items():
        tail_leaf = tail[key][:n_extra]
        if key in _RESIDUAL_OUT_KEYS:
            if noise > 0.0:
                rng, sub = jax.random.split(rng)
                tail_leaf = (
                    jax.random.normal(sub, tail_leaf.shape) * noise
                ).astype(tail_leaf.dtype)
            else:
                tail_leaf = jnp.zeros_like(tail_leaf)
        blocks[key] = jnp.concatenate(
            [jnp.asarray(head_leaf), tail_leaf], axis=0
        )
    target_params = {
        **{k: v for k, v in params.items() if k != "blocks"},
        "blocks": blocks,
    }
    return target, target_params
