"""The serve loop: compiled programs + continuous batching + SLO stats.

Steady-state shape discipline (the whole point): after warmup the
engine dispatches exactly TWO program families —

* one **prefill program per bucket length** (a handful, compiled on
  first use of each bucket);
* ONE **fixed-width decode program** over the ``num_slots`` slot set.

Join-on-arrival, evict-on-finish, growth and preemption all happen
host-side between steps by mutating the programs' int32 operands
(block tables, sequence lengths, current tokens) — never a shape, so
steady-state serving triggers ZERO recompiles (asserted by the bench
and the serve test suite via the telemetry recompile counter).

The engine is driver-side and single-threaded over the device: call
:meth:`step` yourself (tests, bench inner loops) or :meth:`start` a
background thread (`serve_forever` semantics).  Requests arrive either
in-process (:meth:`submit`) or over the DriverQueue plane
(:meth:`queue_handle` + ``serve/client.py``) — same admission path,
same backpressure.

Disaggregated mode (``serve/dist/``): the inbox also accepts
``serve_kv_handoff`` items — a request a PREFILL WORKER already ran,
its per-layer KV blocks and final-position logits riding the queue
plane.  Admission then scatters the blocks into this engine's own pool
(``kv_cache.import_blocks`` — one compiled program per bucket block
count, like the prefill set) and samples the first token from the
shipped logits, so the request goes straight to the fixed-width
decode/verify programs with ZERO extra recompiles.  Wire requests may
also PRESET ``sample_seed`` (the router's fleet-wide submission
ordinal) so a failover re-submission to a different replica replays
the identical sampling stream.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_lightning_tpu.fault.inject import (
    FaultBlackhole, FaultInjected, fire as _fault_fire, set_member,
)
from ray_lightning_tpu.telemetry.propagate import (
    child_context, trace_args,
)

__all__ = ["ServeConfig", "ServeEngine", "ServeHandle", "ServeRejected"]


class ServeRejected(RuntimeError):
    """Admission backpressure: the queue is full (or the request
    expired before admission).  Typed so clients can retry-with-backoff
    without string-matching."""


@dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (docs/SERVING.md "Knobs")."""

    # Decode width: concurrent sequences in flight.  The ONE decode
    # program is compiled at this width; admissions only fill slots.
    num_slots: int = 8
    # Tokens per KV block.  Smaller = finer pool granularity, larger =
    # fewer scatter/gather indices per sequence.
    block_size: int = 16
    # Physical blocks in the pool (block 0 is the trash block).  None =
    # enough for every slot at max_model_len plus one admission's worth
    # of headroom — preemption-free at full width.
    num_blocks: Optional[int] = None
    # Longest prompt+generation the engine admits.  None = the model's
    # positional table (cfg.seq_len).
    max_model_len: Optional[int] = None
    # Prefill bucket lengths (multiples of block_size).  None =
    # power-of-two block counts up to max_model_len.
    prefill_buckets: Optional[Sequence[int]] = None
    # Admission-queue bound: submissions beyond it are REJECTED
    # synchronously (backpressure, never silent queue bloat).
    max_queue: int = 64
    # Multi-tenant LoRA multiplexing (serve/lora.py): capacity of the
    # resident adapter pool (0 = no pool — the engine's program set is
    # byte-identical to pre-LoRA rounds) and the stacked-buffer rank
    # every loaded adapter must match.  Adapters ride every dispatch
    # as a per-slot int32 OPERAND, so any tenant mix shares the
    # compiled-once program set (zero steady-state recompiles).
    max_adapters: int = 0
    adapter_rank: int = 0
    # Per-tenant admission bound: one adapter's burst beyond it is
    # REJECTED while other tenants keep their queue seats (None = the
    # shared max_queue only).
    max_queue_per_adapter: Optional[int] = None
    # Speculative decoding: default drafted tokens per tick when a
    # draft model is loaded (the verify program's width is spec_k + 1).
    # Requires draft_module/draft_params at engine build; per-request
    # ``spec=`` overrides downward (0 = plain target decode).
    spec_k: int = 0
    # Prefix-aware KV reuse (kv_cache.PrefixIndex): resident prompt
    # chains stay in the pool after their requests finish, and a new
    # request's prefill skips every whole block it shares with one —
    # the shared prefix is claimed by refcount bumps (zero device
    # work), only the uncovered suffix is computed.  False keeps the
    # engine byte-identical to pre-cache rounds.
    prefix_cache: bool = False
    # Chunked prefill width (tokens, a multiple of block_size): prompts
    # whose uncovered suffix exceeds it are prefilled one fixed-width
    # chunk per engine step, interleaved with decode ticks, so a long
    # prompt never head-of-line-blocks the resident decode slots — and
    # prompts past the largest prefill bucket become admissible (up to
    # max_model_len).  None = whole-prompt bucketed prefill only.
    prefill_chunk: Optional[int] = None
    # Sampling seed for temperature>0 requests.
    seed: int = 0
    # Background-thread idle sleep between polls when no work exists.
    idle_wait_s: float = 0.002
    # Live-export refresh cadence (prom textfile / serve-live.json).
    export_every_s: float = 1.0
    # Fleet SLO & capacity plane (docs/OBSERVABILITY.md "SLO, burn
    # rate & capacity"): the headroom oracle (serve/capacity.py) and
    # the burn-rate evaluator (telemetry/slo.py) tick on the export
    # cadence.  OFF by default — disabled engines keep snapshot() and
    # serve-live.json byte-identical to pre-plane rounds.
    capacity: bool = False
    slo: bool = False
    # Time-series bin width for the plane's store (RLT_TS_INTERVAL_S).
    ts_interval_s: float = 1.0
    # Queue-wait bound (ms) for the stock serve_queue_wait SLO.
    slo_queue_wait_ms: float = 500.0
    # Override the stock SLOs' (fast_s, slow_s, burn-bound) window
    # pairs.  None = telemetry/slo.py defaults (minutes-scale);
    # benches shrink them to their arm horizons.
    slo_windows: Optional[Tuple[Tuple[float, float, float], ...]] = None


class ServeHandle:
    """Host-side future for one request."""

    def __init__(self, rid: str, request):
        self.rid = rid
        self.request = request
        self.error: Optional[BaseException] = None  # engine-death only
        self._done = threading.Event()

    @property
    def status(self) -> str:
        return self.request.state.value

    @property
    def tokens(self) -> List[int]:
        return list(self.request.generated)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Generated tokens (prompt excluded).  Raises
        :class:`ServeRejected` on backpressure/expiry, ``TimeoutError``
        when the engine did not finish in time."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.rid} not finished within {timeout}s "
                f"(state={self.status})"
            )
        if self.error is not None:
            raise RuntimeError(
                f"serve engine died with request {self.rid} in flight"
            ) from self.error
        if self.request.done_reason in ("rejected", "expired"):
            raise ServeRejected(
                f"request {self.rid} {self.request.done_reason}"
            )
        return list(self.request.generated)


@dataclass
class _PrefillJob:
    """One chunked prefill in flight (engine-internal): the request,
    its private block-table row (the scheduler row stays trashed until
    the last chunk lands), and the first prompt position not yet
    written."""

    req: Any
    row: Any
    next_pos: int


class ServeEngine:
    """Continuous-batching inference engine for one GPT module."""

    def __init__(self, module, params, config: Optional[ServeConfig] = None,
                 telemetry_dir: Optional[str] = None,
                 prom_file: Optional[str] = None,
                 prom_port: Optional[int] = None,
                 draft_module=None, draft_params=None,
                 trace_dir: Optional[str] = None,
                 trace_name: Optional[str] = None,
                 adapters: Optional[Dict[str, dict]] = None):
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.models.generate import _reject_unmerged_lora
        from ray_lightning_tpu.models.quant import (
            dequantize_decode_params, is_quantized,
        )
        from ray_lightning_tpu.serve.kv_cache import PagedKVCache
        from ray_lightning_tpu.serve.metrics import ServeStats
        from ray_lightning_tpu.serve.scheduler import (
            Scheduler, derive_geometry,
        )

        def _prep(tree):
            tree = jax.tree.map(jnp.asarray, tree)
            # Same backend gate as generate(): off-TPU, per-token
            # dequant inside the decode program costs more than the
            # weight-bandwidth it saves — hoist it once at engine build.
            if is_quantized(tree) and jax.default_backend() != "tpu":
                tree = dequantize_decode_params(tree)
            return tree

        self.module = module
        self.cfg = module.config
        self.config = cfg = config or ServeConfig()
        _reject_unmerged_lora(params)
        self.params = _prep(params)
        self._c = module._compute_dtype()
        if (draft_module is None) != (draft_params is None):
            raise ValueError(
                "draft_module and draft_params come as a pair"
            )
        if cfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {cfg.spec_k}")
        if cfg.spec_k > 0 and draft_module is None:
            raise ValueError(
                "spec_k > 0 needs a draft model: pass draft_module/"
                "draft_params (serve/draft.py builds one from the "
                "target)"
            )
        if draft_module is not None and cfg.spec_k < 1:
            raise ValueError(
                "a draft model without spec_k >= 1 would never be "
                "consulted — set ServeConfig(spec_k=K)"
            )
        # Multi-tenant LoRA: the resident adapter pool (None = no
        # multiplexing; every program stays byte-identical to
        # pre-LoRA rounds).  Base params stay lora-FREE either way —
        # _reject_unmerged_lora above guards the truly-unsupported
        # case (adapters smuggled in as the base tree).
        self.adapters = None
        if cfg.max_adapters > 0:
            from ray_lightning_tpu.serve.lora import AdapterPool

            if cfg.adapter_rank < 1:
                raise ValueError(
                    "max_adapters > 0 needs adapter_rank >= 1 (the "
                    "stacked-buffer rank every adapter shares)"
                )
            self.adapters = AdapterPool(
                self.cfg, cfg.max_adapters, cfg.adapter_rank,
                dtype=self._c,
            )
            for name, adapter in (adapters or {}).items():
                self.adapters.add(name, adapter)
        elif adapters:
            raise ValueError(
                "adapters= passed but ServeConfig.max_adapters is 0 — "
                "size the pool (max_adapters/adapter_rank) to serve "
                "multi-tenant LoRA"
            )
        self.draft_module = draft_module
        self.draft_params = None
        if draft_module is not None:
            if draft_module.config.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft_module.config.vocab_size}) != "
                    f"target vocab ({self.cfg.vocab_size}) — drafted "
                    f"tokens would not be target tokens"
                )
            _reject_unmerged_lora(draft_params)
            self.draft_params = _prep(draft_params)
            self._draft_c = draft_module._compute_dtype()
        self.spec_k = cfg.spec_k if draft_module is not None else 0

        if (cfg.max_model_len or 0) > self.cfg.seq_len:
            raise ValueError(
                f"max_model_len {cfg.max_model_len} exceeds the "
                f"positional table ({self.cfg.seq_len})"
            )
        # Shared derivation rule (scheduler.derive_geometry): prefill
        # workers run the SAME function, so handoff geometry can never
        # drift between a worker and its replicas.
        self.max_model_len, buckets = derive_geometry(cfg, self.cfg)
        blocks_per_seq = -(-self.max_model_len // cfg.block_size)
        num_blocks = cfg.num_blocks
        if num_blocks is None:
            # Preemption-free at full width: every slot at max length,
            # one extra admission's worth of blocks, plus the trash
            # block.
            num_blocks = (cfg.num_slots + 1) * blocks_per_seq + 1
        if num_blocks - 1 < blocks_per_seq:
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold even one "
                f"max-length sequence ({blocks_per_seq} blocks)"
            )
        self.cache = PagedKVCache(
            self.cfg, num_blocks, cfg.block_size, dtype=self._c
        )
        # The longest RETAINED bucket bounds the admissible prompt
        # length — submit() enforces it, so Scheduler.bucket_for can
        # never raise inside the serve loop.
        self.max_prompt_len = buckets[-1]
        self.scheduler = Scheduler(
            cfg.num_slots, self.cache.allocator, cfg.block_size,
            blocks_per_seq, buckets, max_queue=cfg.max_queue,
            max_queue_per_adapter=cfg.max_queue_per_adapter,
        )
        # Prefix-aware KV reuse + chunked prefill (docs/SERVING.md
        # "Prefix caching & chunked prefill").  All host-side wiring:
        # the claim hands the scheduler refcount-bumped block ids, the
        # reclaim hook lets pool pressure evict resident chains before
        # any running request is preempted, and chunk_width routes
        # long-suffix admissions to exact block coverage.
        self._chunk = None
        if cfg.prefill_chunk is not None:
            self._chunk = int(cfg.prefill_chunk)
            if self._chunk < cfg.block_size \
                    or self._chunk % cfg.block_size:
                raise ValueError(
                    f"prefill_chunk {cfg.prefill_chunk} must be a "
                    f"positive multiple of block_size {cfg.block_size}"
                )
            if self._chunk > self.max_model_len:
                raise ValueError(
                    f"prefill_chunk {cfg.prefill_chunk} exceeds "
                    f"max_model_len {self.max_model_len}"
                )
            self.scheduler.chunk_width = self._chunk
        self.prefix_cache = None
        if cfg.prefix_cache:
            from ray_lightning_tpu.serve.kv_cache import PrefixIndex

            self.prefix_cache = PrefixIndex(
                self.cache.allocator, cfg.block_size
            )
            self.scheduler.claim_fn = self._claim_prefix
            self.scheduler.reclaim = self.prefix_cache.evict
        # In-flight chunked prefills, keyed by slot.  While a job runs,
        # the slot's scheduler row points at the trash block and its
        # seq_len is 0 — the decode program treats it exactly like an
        # inactive slot (writes trashed, sampled token ignored), so the
        # job needs no change to the compiled decode graph.
        self._chunk_jobs: Dict[int, "_PrefillJob"] = {}
        # Adapter names whose cached chains must be dropped before the
        # next admission poll: add/remove_adapter run on OTHER threads,
        # and every PrefixIndex mutation belongs to the serve thread —
        # so they queue the invalidation here (under self._lock) and
        # step() drains it under the SAME lock hold as poll(), which
        # orders the drop strictly before any claim against the new
        # factors.
        self._prefix_drops: List[str] = []
        self.stats = ServeStats()
        self._pool = self.cache.init_pool()
        self._draft_pool = None
        if draft_module is not None:
            dcfg = draft_module.config
            if dcfg.seq_len < self.max_model_len:
                raise ValueError(
                    f"draft positional table ({dcfg.seq_len}) shorter "
                    f"than max_model_len ({self.max_model_len})"
                )
            # The draft pool mirrors the target pool's block geometry
            # (same num_blocks, same block_size) and SHARES the slot
            # block tables — one allocator, one coverage/rollback
            # arithmetic, two pools.
            self._draft_cache = PagedKVCache(
                dcfg, num_blocks, cfg.block_size, dtype=self._draft_c
            )
            self._draft_pool = self._draft_cache.init_pool()
        self._cur_tokens = np.zeros((cfg.num_slots,), np.int32)
        self._started_t = time.monotonic()
        # Request-scoped distributed tracing (docs/OBSERVABILITY.md
        # "Distributed tracing"): wall-clock spans per critical-path
        # phase, exported as trace-serve-<name>.jsonl at stop() for
        # telemetry/trace_collect.py to stitch.  OFF unless trace_dir
        # is set — the disabled tracer costs one attribute check.
        from ray_lightning_tpu.telemetry.spans import SpanTracer

        self._trace_dir = trace_dir
        self._trace_name = trace_name or uuid.uuid4().hex[:6]
        self.tracer = SpanTracer(
            enabled=trace_dir is not None, maxlen=16384, rank=0,
            clock=time.time,
        )
        self._build_programs()

        self._handles: Dict[str, ServeHandle] = {}  # guarded by self._lock
        # Terminal (rid, status) pairs since the last drain_done() —
        # the completion feed a disaggregated replica's beats carry so
        # the router can prune its in-flight tracking.  Bounded: an
        # undreained feed (no router) must never grow without bound.
        self._done_feed: deque = deque(maxlen=4096)  # guarded by self._lock
        # Non-terminal (rid, error) handoff-admission failures — fed to
        # the router by replica beats (``failed`` key) so it re-routes
        # the PREFILL instead of failing the request terminally.  Only
        # populated when a replica runner opts in below.
        self._failed_feed: deque = deque(maxlen=4096)  # guarded by self._lock
        # Disaggregated-replica mode: a torn/vanished handoff payload
        # becomes a beat-reported retryable failure (router re-routes
        # the prefill) instead of a terminal ``invalid`` reply.  The
        # replica runner flips this on; a standalone queue-plane engine
        # keeps the terminal-reply behavior.
        self.report_handoff_failures = False
        # Serve-fleet identity for the fault grammar: the runner sets
        # ("decode", replica_id) so the serve THREAD (started later,
        # from start()) can declare itself to the thread-local member
        # context in fault/inject.py.
        self.fault_member: Optional[Tuple[str, str]] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inbox = None           # DriverQueue, lazily created
        # Handoffs whose tenant's serve_adapter_load frame has not
        # landed yet (the worker's handoff rides its OWN connection and
        # can outrun the router's load frame): re-tried each drain for
        # a bounded number of cycles before the typed-invalid fallback.
        # Serve-loop-thread only — never shared, no lock.
        self._deferred_inbox: deque = deque()
        # Serve-thread send cache; stop() closes it from the
        # caller's thread after a join(timeout) that a wedged
        # dispatch can outlive — so it shares the lock.
        # guarded by self._lock
        self._reply_handles: Dict[Tuple[str, int], Any] = {}
        self._exporter = None
        self._live_path = None
        self._last_export = 0.0
        if prom_file or prom_port is not None:
            from ray_lightning_tpu.telemetry.export_prom import PromExporter

            self._exporter = PromExporter(
                textfile=prom_file, port=prom_port
            )
        if telemetry_dir:
            import os

            os.makedirs(telemetry_dir, exist_ok=True)
            self._live_path = f"{telemetry_dir}/serve-live.json"
        # Fleet SLO & capacity plane: headroom oracle + burn-rate
        # evaluator, ticked by _maybe_export on the export cadence —
        # host-side dict folds only, zero new device work, so the
        # recompile counter stays pinned with the plane on.
        self._capacity = None
        self._slo = None
        self._slo_alerts: deque = deque(maxlen=256)
        if cfg.capacity or cfg.slo:
            from ray_lightning_tpu.serve.capacity import CapacityOracle

            self._capacity = CapacityOracle(
                interval_s=cfg.ts_interval_s, clock=time.time,
            )
            # Derived capacity snapshots (model fit + trends over
            # every series) refresh at ~1 Hz no matter how fast the
            # export tick runs; beats and exports reuse the cached
            # result in between.
            self._capacity_every_s = max(cfg.export_every_s, 1.0)
            self._last_capacity = 0.0
        if cfg.slo:
            import dataclasses

            from ray_lightning_tpu.telemetry.slo import (
                SloEvaluator, default_serve_slos,
            )

            specs = default_serve_slos(cfg.slo_queue_wait_ms)
            if cfg.slo_windows is not None:
                windows = tuple(tuple(w) for w in cfg.slo_windows)
                specs = tuple(
                    dataclasses.replace(s, windows=windows)
                    for s in specs
                )
            self._slo = SloEvaluator(
                self._capacity.store, specs,
                clock=time.time, emit=self._slo_alerts.append,
            )

    # -- compiled programs ---------------------------------------------------
    def _build_programs(self) -> None:
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.serve.kv_cache import (
            import_blocks, make_slot_keys, paged_decode_step,
            paged_prefill, paged_verify_step, sample_tokens,
        )
        from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit

        cfg, c = self.cfg, self._c
        base_key = jax.random.PRNGKey(self.config.seed)
        # Donation keeps the pool update in place on TPU; XLA:CPU cannot
        # donate and would warn on every dispatch.
        donate = (1,) if jax.default_backend() == "tpu" else ()
        # Multi-tenant LoRA: the BGMV arm is resolved ONCE here (probe
        # or RLT_LORA_BGMV), then closed over — never re-decided on the
        # dispatch path.  Pool-less engines trace with adapters=None,
        # keeping their graphs byte-identical to pre-LoRA rounds.
        lora_impl = self.adapters.impl if self.adapters is not None \
            else "xla"

        def _decode(params, pool, block_tables, seq_lens, tokens, temps,
                    seeds, top_ks, ad, ad_ids):
            logits, pool = paged_decode_step(
                cfg, params, pool, block_tables, seq_lens, tokens,
                compute_dtype=c, adapters=ad, adapter_ids=ad_ids,
                lora_impl=lora_impl,
            )
            keys = make_slot_keys(base_key, seeds, seq_lens)
            return sample_tokens(logits, keys, temps, top_ks), pool

        def _prefill(params, pool, tokens, prompt_len, block_ids, temp,
                     seed, top_k, ad, ad_id):
            logits, pool = paged_prefill(
                cfg, params, pool, tokens, prompt_len, block_ids,
                compute_dtype=c, adapters=ad, adapter_id=ad_id,
                lora_impl=lora_impl,
            )
            keys = make_slot_keys(
                base_key, seed[None], (prompt_len - 1)[None]
            )
            first = sample_tokens(
                logits[None], keys, temp[None], top_k[None]
            )[0]
            return first, pool

        def _first(logits, prompt_len, temp, seed, top_k):
            # Disaggregated admission: the prefill worker shipped the
            # final-position logits with the KV blocks; sampling them
            # HERE with this engine's keys is bitwise the tail of
            # _prefill — local and imported admissions emit identical
            # first tokens.
            keys = make_slot_keys(
                base_key, seed[None], (prompt_len - 1)[None]
            )
            return sample_tokens(
                logits[None], keys, temp[None], top_k[None]
            )[0]

        self._decode_fn = ledgered_jit(
            _decode, site="serve/decode", donate_argnums=donate
        )
        # One python callable; XLA compiles one executable per bucket
        # length (tokens/block_ids shapes) — the bucketed prefill set
        # lands in the program ledger as one site with a variant per
        # bucket.
        self._prefill_fn = ledgered_jit(
            _prefill, site="serve/prefill", donate_argnums=donate
        )
        # Disaggregated KV import: one executable per bucket block
        # count (block_ids shape), mirroring the prefill set — fleet
        # warmup compiles them all, steady state never recompiles.
        self._import_fn = ledgered_jit(
            import_blocks, site="serve/kv_import",
            donate_argnums=(0,) if jax.default_backend() == "tpu" else (),
        )
        self._first_fn = ledgered_jit(_first, site="serve/first_token")

        def _chunk_prefill(params, pool, table_row, start, tokens, limit,
                           sample_idx, temp, seed, top_k, ad, ad_ids):
            # One prompt chunk through the verify program at W=1: the
            # window writes k/v at positions start + [0, Tc) into the
            # slot's blocks (write_limit trashes the padding tail) and
            # attends under the same causal frontier the bucketed
            # prefill enforces — so a prompt computed suffix-only over
            # claimed prefix blocks, or chunk by chunk, fills the cache
            # with the same values.  ``sample_idx`` picks the window
            # position whose logits produce the first token (the final
            # chunk passes prompt_len - 1 - start; earlier chunks pass
            # 0 and ignore the token) with the request's position-keyed
            # stream — bitwise the tail of _prefill.
            logits, pool = paged_verify_step(
                cfg, params, pool, table_row, start, tokens, limit,
                compute_dtype=c, adapters=ad, adapter_ids=ad_ids,
                lora_impl=lora_impl,
            )
            pick = jax.lax.dynamic_index_in_dim(
                logits[0], sample_idx, axis=0, keepdims=False
            )
            keys = make_slot_keys(
                base_key, seed[None], (start[0] + sample_idx)[None]
            )
            tok = sample_tokens(
                pick[None], keys, temp[None], top_k[None]
            )[0]
            return tok, pool

        # Compiled per chunk width: the fixed prefill_chunk width for
        # jobs plus one per bucket used by inline suffix computes — a
        # bounded set, warmed on first use like the prefill buckets.
        self._chunk_fn = ledgered_jit(
            _chunk_prefill, site="serve/chunk_prefill",
            donate_argnums=donate,
        )

        if self.draft_module is None:
            return
        dcfg, dc = self.draft_module.config, self._draft_c
        K = self.spec_k

        def _draft_prefill(dparams, dpool, tokens, prompt_len, block_ids):
            _, dpool = paged_prefill(
                dcfg, dparams, dpool, tokens, prompt_len, block_ids,
                compute_dtype=dc,
            )
            return dpool

        def _draft_step(dparams, dpool, block_tables, positions, prev,
                        override, use_override, limits):
            # The chain's token source is resolved ON DEVICE so the
            # K+1 dispatches never round-trip to the host: dispatch 0
            # feeds the host-provided start token, dispatch 1 feeds the
            # current token on slots that spent dispatch 0 syncing the
            # bonus-token position, everything later feeds the previous
            # dispatch's own greedy proposal.
            tokens = jnp.where(use_override, override, prev)
            logits, dpool = paged_decode_step(
                dcfg, dparams, dpool, block_tables, positions, tokens,
                compute_dtype=dc, write_limit=limits,
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), dpool

        def _verify(params, pool, block_tables, seq_lens, tokens, limits,
                    temps, seeds, top_ks, ad, ad_ids):
            logits, pool = paged_verify_step(
                cfg, params, pool, block_tables, seq_lens, tokens,
                limits, compute_dtype=c, adapters=ad,
                adapter_ids=ad_ids, lora_impl=lora_impl,
            )
            W, T = tokens.shape
            pos = (seq_lens[:, None] + jnp.arange(T)).reshape(-1)
            keys = make_slot_keys(
                base_key, jnp.repeat(seeds, T), pos
            )
            sampled = sample_tokens(
                logits.reshape(W * T, -1), keys,
                jnp.repeat(temps, T),
                None if top_ks is None else jnp.repeat(top_ks, T),
            )
            return sampled.reshape(W, T), pool

        def _draft_chunk(dparams, dpool, table_row, start, tokens, limit):
            # The draft-pool mirror of _chunk_prefill: same window, same
            # blocks (the draft cache shares the slot block tables), so
            # a claimed/chunked admission leaves the draft frontier
            # exactly where a bucketed _draft_prefill would have.
            _, dpool = paged_verify_step(
                dcfg, dparams, dpool, table_row, start, tokens, limit,
                compute_dtype=dc,
            )
            return dpool

        self._draft_prefill_fn = ledgered_jit(
            _draft_prefill, site="serve/draft_prefill",
            donate_argnums=donate,
        )
        self._draft_step_fn = ledgered_jit(
            _draft_step, site="serve/draft_step", donate_argnums=donate
        )
        self._draft_chunk_fn = ledgered_jit(
            _draft_chunk, site="serve/draft_chunk", donate_argnums=donate
        )
        self._verify_fn = ledgered_jit(
            _verify, site="serve/verify", donate_argnums=donate
        )
        self._spec_width = K + 1

    # -- submission ----------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               top_k: Optional[int] = None,
               spec: Optional[int] = None,
               adapter: Optional[str] = None,
               deadline_s: Optional[float] = None,
               sample_seed: Optional[int] = None,
               on_token=None, rid: Optional[str] = None,
               _handoff: Optional[dict] = None,
               _trace_ctx=None) -> ServeHandle:
        """Enqueue one request (thread-safe).  Returns a handle; a
        backpressure rejection is visible immediately as
        ``handle.status == "rejected"`` (and ``result()`` raises).

        ``spec`` caps this request's speculative draft count: None =
        the engine's ``spec_k`` default, 0 = plain target decode, K =
        at most K drafted tokens verified per tick (clamped to the
        engine width).

        ``adapter`` decodes this request through the named tenant's
        LoRA adapter (the pool's per-slot gathered delta, slot 0 for
        None) — unknown or pool-less names are typed ``ValueError``
        rejections (the queue plane surfaces them as ``invalid``
        replies), never silent base-model fallbacks.

        ``sample_seed`` presets the request's sampling-stream identity
        (None = this engine's submission ordinal).  The disaggregated
        router assigns fleet-wide seeds so re-submitting a failed-over
        request to ANY replica replays the identical token stream.

        ``_handoff`` (internal, ``serve/dist/``) carries a prefill
        worker's exported KV payload — admission imports it instead of
        running the local prefill program."""
        from ray_lightning_tpu.serve.scheduler import Request

        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if top_k is not None:
            top_k = int(top_k)
            if top_k < 1:
                raise ValueError(f"top_k must be >= 1, got {top_k}")
            if temperature <= 0.0:
                raise ValueError(
                    "top_k requires temperature > 0 (temperature=0 is "
                    "greedy decoding, which would silently ignore it)"
                )
        if spec is not None:
            spec = int(spec)
            if spec < 0:
                raise ValueError(f"spec must be >= 0, got {spec}")
            if spec > 0 and self.draft_module is None:
                raise ValueError(
                    "spec > 0 on an engine without a draft model — "
                    "build the ServeEngine with draft_module/draft_params"
                )
        if sample_seed is not None:
            sample_seed = int(sample_seed)
            if sample_seed < 0:
                raise ValueError(
                    f"sample_seed must be >= 0, got {sample_seed}"
                )
        if adapter is not None:
            adapter = str(adapter)
            if self.adapters is None:
                raise ValueError(
                    f"request names adapter {adapter!r} but this engine "
                    f"has no adapter pool — build it with "
                    f"ServeConfig(max_adapters=N, adapter_rank=r)"
                )
        if len(prompt) + max_new_tokens > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})"
            )
        if len(prompt) > self.max_prompt_len and self._chunk is None:
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds the largest prefill "
                f"bucket ({self.max_prompt_len}); raise max_model_len "
                f"to a multiple of block_size, pass prefill_buckets, "
                f"or enable chunked prefill (ServeConfig.prefill_chunk)"
            )
        if any(not 0 <= t < self.cfg.vocab_size for t in prompt):
            raise ValueError("prompt token outside the vocab")
        if self._error is not None:
            raise RuntimeError(
                "serve engine is dead (its loop raised; see the chained "
                "error) — build a fresh ServeEngine"
            ) from self._error
        rid = rid or uuid.uuid4().hex[:12]
        trace_ctx, trace_local = _trace_ctx, False
        if trace_ctx is None and self.tracer.enabled:
            # No upstream context (in-process submission on a tracing
            # engine): this engine owns the trace root.
            from ray_lightning_tpu.telemetry.propagate import root_context

            trace_ctx, trace_local = root_context(rid), True
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(temperature), eos_token_id=eos_token_id,
            top_k=top_k, spec=spec, adapter=adapter,
            deadline_s=deadline_s, sample_seed=sample_seed,
            on_token=on_token, trace=trace_ctx,
        )
        req._trace_local = trace_local
        if _handoff is not None:
            req._handoff = _handoff
        handle = ServeHandle(rid, req)
        with self._lock:
            if adapter is not None:
                # Resolved under the SAME lock that enqueues: a
                # remove_adapter/add_adapter on another thread either
                # completes first (unknown name -> the typed rejection
                # below) or sees this request via references_adapter —
                # a slot can never be re-issued to a new tenant while a
                # request resolved against the old one is in flight.
                try:
                    req._adapter_slot = self.adapters.slot_of(adapter)
                except KeyError:
                    raise ValueError(
                        f"unknown adapter {adapter!r} — hot-load it "
                        f"first (engine.add_adapter / "
                        f"serve_adapter_load frame)"
                    ) from None
            self.stats.bump("submitted")
            accepted = self.scheduler.submit(req)
            if accepted:
                self._handles[rid] = handle
            else:
                self._done_feed.append((rid, "rejected"))
        if not accepted:
            self.stats.bump("rejected")
            req.finished_t = time.monotonic()
            handle._done.set()
        return handle

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 60.0, **kw) -> List[int]:
        """Blocking convenience: submit + drive (when no background
        thread runs) + result."""
        handle = self.submit(prompt, max_new_tokens, **kw)
        if self._thread is None:
            self.run_until_idle()
        return handle.result(timeout)

    # -- the loop ------------------------------------------------------------
    def step(self) -> bool:
        """One serve iteration: drain the queue plane, expire/admit,
        grow/preempt, one decode (or draft→verify) tick.  Returns True
        when any work was done (False = idle)."""
        import jax.numpy as jnp

        _fault_fire("replica_tick")
        self._drain_inbox()
        with self._lock:
            if self.prefix_cache is not None and self._prefix_drops:
                # Invalidate replaced/removed tenants' chains BEFORE
                # admitting: adapter-keyed KV must never be claimed
                # against different factors than wrote it.
                for name in self._prefix_drops:
                    self.prefix_cache.drop(name)
                self._prefix_drops.clear()
            admissions, expired = self.scheduler.poll()
        worked = bool(admissions) or bool(expired)
        for req in expired:
            self.stats.bump("expired")
            self._finish_handle(req)
        now = time.monotonic()
        t_adm = now
        tr = self.tracer
        for slot, req, bucket in admissions:
            wait = now - req.arrival_t
            self.stats.note_admitted(wait)
            ctx = req.trace if tr.enabled else None
            if ctx is not None:
                tr.record(
                    "queue_wait", time.time() - wait, wait,
                    args=trace_args(child_context(ctx), rid=req.rid,
                                    preemptions=req.preemptions),
                )
                self.stats.note_phase("queue_wait", wait)
            if bucket == 0:
                # Prefix-claimed and/or chunked admission (exact block
                # coverage, no bucket padding): the uncovered suffix
                # runs through the fixed-width chunk program — inline
                # when it fits one dispatch, one chunk per step
                # (interleaved with decode ticks) otherwise.
                suffix_len = req.prompt_len - req.claimed_tokens
                if self._chunk is not None and suffix_len > self._chunk:
                    self._start_chunk_job(slot, req)
                    continue
                handoff = None
                self.stats.bump("prefills")
                t_ph = time.time() if ctx is not None else 0.0
                first = self._suffix_prefill(slot, req)
            else:
                ids = np.asarray(  # rlt: noqa[RLT002] host block list, no device value
                    self.scheduler._blocks[slot][: bucket
                                                 // self.config.block_size],
                    np.int32,
                )
                ids = jnp.asarray(ids)
                handoff = getattr(req, "_handoff", None)
                padded = None
                if handoff is None or self.draft_module is not None:
                    # The padded prompt feeds the local prefill and/or
                    # the draft prefill; a KV import on a draft-less
                    # engine — the disaggregated steady state — needs
                    # neither, so skip the bucket-sized host→device
                    # copy entirely.
                    padded_np = np.zeros((bucket,), np.int32)
                    padded_np[: req.prompt_len] = req.prompt
                    padded = jnp.asarray(padded_np)
                t_ph = time.time() if ctx is not None else 0.0
            if bucket != 0 and handoff is not None:
                # A prefill worker already ran this prompt: scatter its
                # exported blocks into OUR allocator's blocks and
                # sample the first token from the shipped logits —
                # bitwise what the local prefill would have produced,
                # without the trunk forward.
                req._handoff = None  # the payload is large; drop it
                self.stats.bump("kv_imports")
                self._pool = self._import_fn(
                    self._pool,
                    {k: jnp.asarray(v) for k, v in handoff["kv"].items()},
                    ids,
                )
                first = self._first_fn(
                    jnp.asarray(handoff["logits"]),
                    np.int32(req.prompt_len),
                    np.float32(req.temperature),
                    np.int32(req.sample_seed), np.int32(req.top_k or 0),
                )
            elif bucket != 0:
                self.stats.bump("prefills")
                ad = None if self.adapters is None \
                    else self.adapters.buffers
                ad_id = None if self.adapters is None \
                    else np.int32(req._adapter_slot)
                first, self._pool = self._prefill_fn(
                    self.params, self._pool, padded,
                    np.int32(req.prompt_len), ids,
                    np.float32(req.temperature),
                    np.int32(req.sample_seed),
                    np.int32(req.top_k or 0),
                    ad, ad_id,
                )
            if bucket != 0 and self.draft_module is not None:
                # The draft cache tracks every admission (one bucketed
                # draft-prefill program per bucket) so any later tick
                # can speculate for this slot.
                self._draft_pool = self._draft_prefill_fn(
                    self.draft_params, self._draft_pool, padded,
                    np.int32(req.prompt_len), ids,
                )
            first = int(first)  # rlt: noqa[RLT002] deliberate TTFT sync at admission
            t_first = time.monotonic()
            # Per-admission wall in µs (host prep + prefill/import
            # dispatch + the TTFT sync above).  Paired with the
            # `admitted` counter it gives the capacity oracle the
            # once-per-request admission cost its saturation model
            # charges (serve/capacity.py).
            self.stats.bump(  # rlt: noqa[RLT002] host float, no device value
                "admit_us", int((t_first - t_adm) * 1e6))
            t_adm = t_first
            if ctx is not None:
                # The int() above synced the device, so this interval
                # covers dispatch + device compute of the admission.
                t_sync = time.time()
                phase = ("decode_admission" if handoff is not None
                         else "prefill_compute")
                tr.record(phase, t_ph, max(0.0, t_sync - t_ph),
                          args=trace_args(child_context(ctx),
                                          rid=req.rid, bucket=bucket))
                self.stats.note_phase(phase, t_sync - t_ph)
            self.stats.note_first_token(t_first - req.arrival_t)
            done = self.scheduler.append_token(slot, first, now=t_first)
            if ctx is not None:
                ft_dur = max(0.0, time.time() - t_sync)
                tr.record("first_token", t_sync, ft_dur,
                          args=trace_args(child_context(ctx),
                                          rid=req.rid, token_index=0))
                self.stats.note_phase("first_token", ft_dur)
            self.stats.bump("tokens_out")
            if req.adapter is not None:
                self.stats.note_adapter(req.adapter, tokens=1)
            self._cur_tokens[slot] = first
            if self.prefix_cache is not None:
                self._prefix_insert(slot, req)
            if done:
                self._complete(slot)

        # One chunk for every in-flight chunked prefill BEFORE the
        # decode tick: both dispatches queue on the device each step,
        # so resident slots keep emitting one token per step while a
        # long prompt fills in chunk by chunk (the no-stall contract).
        if self._chunk_jobs:
            worked = self._chunk_tick() or worked

        # Per-slot speculative widths for THIS tick: the engine K,
        # capped per request (spec= knob) and by the tokens it has left
        # (a tick never drafts past max_new_tokens).  Zero everywhere
        # when no draft model is loaded.
        widths = self._tick_widths()

        # Growth (and preemption when the pool is dry) for every slot
        # about to write past its allocated blocks.  Preemption is only
        # ever for BASELINE coverage — the one position a plain decode
        # write needs (round-11 semantics, unchanged).  The speculative
        # window is claimed OPPORTUNISTICALLY on top: if the pool can't
        # cover seq_len + width, the slot drafts fewer tokens this tick
        # (down to zero) rather than evicting a neighbour — speculation
        # is a throughput bet, and a bet must never cost another
        # request its progress (two spec slots preempting each other's
        # windows would ping-pong without forward progress).
        active = [
            s for s, r in enumerate(self.scheduler.slots)
            if r is not None and s not in self._chunk_jobs
        ]
        for slot in list(active):
            if self.scheduler.slots[slot] is None:
                continue  # preempted by an earlier slot's growth
            while self.scheduler.needs_block(slot):
                if self.scheduler.grow(slot):
                    break
                victim = self.scheduler.preempt_youngest(protect=slot)
                if victim is None:
                    # Only this request is live and the pool is dry —
                    # impossible under the init-time sizing invariant.
                    raise RuntimeError(
                        "block pool exhausted with a single live "
                        "request — num_blocks below one sequence"
                    )
                self.stats.bump("preempted")
        for slot, req in enumerate(self.scheduler.slots):
            if req is None or widths[slot] == 0:
                continue
            w = widths[slot]
            # rlt: noqa[RLT002] host np state
            seq_len = int(self.scheduler.seq_lens[slot])
            while w > 0 and not self.scheduler.cover(slot, seq_len + w):
                w -= 1  # pool can't fund the window: draft less
            widths[slot] = w

        active = [
            s for s, r in enumerate(self.scheduler.slots)
            if r is not None and s not in self._chunk_jobs
        ]
        if active:
            worked = True
            if any(widths[s] > 0 for s in active):
                self._spec_tick(active, widths)
            else:
                self._decode_tick(active)
        self._refresh_gauges()
        self._maybe_export()
        return worked

    def _tick_widths(self) -> List[int]:
        """Drafted tokens per slot this tick (0 = plain decode)."""
        widths = [0] * self.config.num_slots
        if self.spec_k == 0:
            return widths
        for slot, req in enumerate(self.scheduler.slots):
            if req is None or slot in self._chunk_jobs:
                continue
            k = self.spec_k if req.spec is None else min(
                req.spec, self.spec_k
            )
            remaining = req.max_new_tokens - len(req.generated)
            widths[slot] = max(0, min(k, remaining - 1))
        return widths

    def _lora_operands(self):
        """``(stacked adapter buffers, per-slot adapter_ids operand)``
        for this tick — ``(None, None)`` on pool-less engines, which
        keeps their compiled graphs byte-identical to pre-LoRA rounds.
        The buffers reference is read once per tick: a concurrent hot
        add swaps the pool's (immutable) tree atomically, and a new
        slot cannot appear in ``adapter_slots`` before its add()
        returned — so a tick sees either the old world or the new one,
        never a torn mix."""
        import jax.numpy as jnp

        if self.adapters is None:
            return None, None
        return self.adapters.buffers, jnp.asarray(
            self.scheduler.adapter_slots
        )

    def _tick_top_ks(self):
        """``top_ks`` operand for this tick, or None when NO slot uses
        top-k — the None variant compiles without the full-vocab sort,
        so greedy/temperature-only traffic (the common mix) never pays
        sorted-vocab work per dispatch.  The sorted variant compiles
        once on the first top-k tick, like a fresh prefill bucket."""
        import jax.numpy as jnp

        if not np.any(self.scheduler.top_ks > 0):
            return None
        return jnp.asarray(self.scheduler.top_ks)

    # -- prefix cache + chunked prefill -------------------------------------
    def _claim_prefix(self, req) -> List[int]:
        """Scheduler claim hook: refcount-claim the resident blocks
        covering the longest whole-block shared prefix of ``req``'s
        prompt.  The cap ``(prompt_len - 1) // Bs`` keeps the FINAL
        prompt token's block always computed locally — its forward
        produces the first-token logits, and every later write (decode,
        verify window, chunk) lands strictly PAST the claimed frontier,
        which is why claimed blocks never need copy-on-write in nominal
        serving (``Scheduler.cow_slot`` stays a defensive escape
        hatch).  Handoff admissions never claim: the wire payload
        covers the whole prompt and must scatter into private blocks."""
        if getattr(req, "_handoff", None) is not None:
            return []
        cap = (req.prompt_len - 1) // self.config.block_size
        return self.prefix_cache.claim(req.adapter, req.prompt, cap)

    def _suffix_prefill(self, slot: int, req) -> Any:
        """Prefill the uncovered suffix of a claimed (or
        short-chunkable) admission in ONE chunk-program dispatch and
        return the (device) first token.  The window width is the
        smallest prefill bucket covering the suffix — re-using the
        bucketed shape set — or the fixed chunk width for suffixes past
        the largest bucket, so the executable set stays bounded."""
        import jax.numpy as jnp

        sched = self.scheduler
        start = req.claimed_tokens
        suffix = req.prompt_len - start
        width = next(
            (b for b in sched.buckets if b >= suffix), self._chunk
        )
        window = np.zeros((1, width), np.int32)
        window[0, :suffix] = req.prompt[start:]
        table_row = jnp.asarray(sched.block_tables[slot: slot + 1])
        start_arr = jnp.asarray(np.full((1,), start, np.int32))
        limit = jnp.asarray(np.full((1,), req.prompt_len, np.int32))
        tokens = jnp.asarray(window)
        ad = None if self.adapters is None else self.adapters.buffers
        ad_ids = None if self.adapters is None else jnp.asarray(
            [req._adapter_slot], jnp.int32
        )
        tok, self._pool = self._chunk_fn(
            self.params, self._pool, table_row, start_arr, tokens,
            limit, np.int32(suffix - 1), np.float32(req.temperature),
            np.int32(req.sample_seed), np.int32(req.top_k or 0),
            ad, ad_ids,
        )
        if self.draft_module is not None:
            self._draft_pool = self._draft_chunk_fn(
                self.draft_params, self._draft_pool, table_row,
                start_arr, tokens, limit,
            )
        self.stats.bump("prefill_chunks")
        return tok

    def _start_chunk_job(self, slot: int, req) -> None:
        """Begin a chunked prefill: park the slot OUT of the decode set
        (scheduler row trashed, seq_len 0 — the compiled decode program
        treats it exactly like an inactive slot) and remember its real
        block-table row privately.  One chunk advances per engine step,
        interleaved with decode ticks, so resident decode slots keep
        emitting while a 32k prompt fills in."""
        from ray_lightning_tpu.serve.kv_cache import TRASH_BLOCK

        sched = self.scheduler
        row = sched.block_tables[slot].copy()
        sched.block_tables[slot, :] = TRASH_BLOCK
        sched.seq_lens[slot] = 0
        sched.draft_lens[slot] = 0
        self.stats.bump("prefills")
        self._chunk_jobs[slot] = _PrefillJob(
            req=req, row=row, next_pos=req.claimed_tokens
        )

    def _chunk_tick(self) -> bool:
        """Advance every in-flight chunked prefill by exactly ONE chunk
        (the no-stall contract: a long prompt costs resident decode
        slots one chunk dispatch per step, never the whole prefill).
        The final chunk samples the first token (bitwise the tail of
        the bucketed prefill), restores the slot's scheduler row, and
        hands the request to the ordinary decode path."""
        import jax.numpy as jnp

        if not self._chunk_jobs:
            return False
        sched = self.scheduler
        worked = False
        for slot, job in list(self._chunk_jobs.items()):
            if sched.slots[slot] is not job.req:
                # The request was preempted (or force-finished) out
                # from under the job: its blocks are already freed and
                # a requeued re-admission restarts cleanly, so the
                # stale job is simply dropped.
                del self._chunk_jobs[slot]
                continue
            req = job.req
            start = job.next_pos
            width = self._chunk
            end = min(start + width, req.prompt_len)
            last = end == req.prompt_len
            window = np.zeros((1, width), np.int32)
            window[0, : end - start] = req.prompt[start:end]
            table_row = jnp.asarray(job.row[None, :])
            start_arr = jnp.asarray(np.full((1,), start, np.int32))
            limit = jnp.asarray(np.full((1,), end, np.int32))
            sample_idx = np.int32(
                (req.prompt_len - 1 - start) if last else 0
            )
            tokens = jnp.asarray(window)
            ad = None if self.adapters is None else self.adapters.buffers
            ad_ids = None if self.adapters is None else jnp.asarray(
                [req._adapter_slot], jnp.int32
            )
            tok, self._pool = self._chunk_fn(
                self.params, self._pool, table_row, start_arr, tokens,
                limit, sample_idx, np.float32(req.temperature),
                np.int32(req.sample_seed), np.int32(req.top_k or 0),
                ad, ad_ids,
            )
            if self.draft_module is not None:
                self._draft_pool = self._draft_chunk_fn(
                    self.draft_params, self._draft_pool, table_row,
                    start_arr, tokens, limit,
                )
            self.stats.bump("prefill_chunks")
            job.next_pos = end
            worked = True
            if not last:
                continue
            # Final chunk landed: the private row goes live and the
            # slot joins the fixed-width decode set next tick.
            del self._chunk_jobs[slot]
            first = int(tok)  # rlt: noqa[RLT002] deliberate TTFT sync at admission
            sched.block_tables[slot, :] = job.row
            sched.seq_lens[slot] = req.prompt_len
            sched.draft_lens[slot] = req.prompt_len
            t_first = time.monotonic()
            self.stats.note_first_token(t_first - req.arrival_t)
            done = sched.append_token(slot, first, now=t_first)
            self.stats.bump("tokens_out")
            if req.adapter is not None:
                self.stats.note_adapter(req.adapter, tokens=1)
            self._cur_tokens[slot] = first
            if self.prefix_cache is not None:
                self._prefix_insert(slot, req)
            if done:
                self._complete(slot)
        return worked

    def _prefix_insert(self, slot: int, req) -> None:
        """Publish the slot's whole-block prompt prefix into the
        cache.  Claimed blocks just re-match during the walk (nothing
        re-stored); freshly computed full blocks are retained by the
        index, so they survive the request's release and the NEXT
        prompt sharing them claims instead of recomputing."""
        n = req.prompt_len // self.config.block_size
        if n == 0:
            return
        self.prefix_cache.insert(
            req.adapter, req.prompt, self.scheduler._blocks[slot][:n]
        )

    def _decode_tick(self, active: List[int]) -> None:
        """One token for every active slot — the non-speculative path
        (and the fallback when no active slot drafts this tick)."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        seq_lens = jnp.asarray(self.scheduler.seq_lens)
        cur = jnp.asarray(self._cur_tokens)
        tables = jnp.asarray(self.scheduler.block_tables)
        ad, ad_ids = self._lora_operands()
        toks, self._pool = self._decode_fn(
            self.params, self._pool, tables, seq_lens, cur,
            jnp.asarray(self.scheduler.temperatures),
            jnp.asarray(self.scheduler.sample_seeds),
            self._tick_top_ks(),
            ad, ad_ids,
        )
        if self.draft_module is not None:
            # Mirror the write into the draft cache so its frontier
            # claim below stays TRUE: a fallback tick on a speculative
            # engine (pool pressure shrank every window to zero) must
            # not leave a silent gap that degrades every later draft
            # proposal for the sequence.
            _, self._draft_pool = self._draft_step_fn(
                self.draft_params, self._draft_pool, tables, seq_lens,
                cur, cur, jnp.ones((self.config.num_slots,), bool),
                seq_lens + 1,
            )
            self.stats.bump("draft_steps")
        # rlt: noqa[RLT002] deliberate: the tick must emit tokens
        toks = np.asarray(toks)
        dt = time.monotonic() - t0
        self.stats.bump("decode_steps")
        # Tick wall in µs — with decode_steps/tokens_out it gives the
        # capacity oracle per-bin (busy slots, tick cost) pairs, the
        # data its affine tick-cost fit needs (serve/capacity.py).
        self.stats.bump(  # rlt: noqa[RLT002] host float, no device value
            "decode_us", int(dt * 1e6))
        self.stats.note_token_latency(dt, n_tokens=len(active))
        for slot in active:
            self.scheduler.seq_lens[slot] += 1
            self.scheduler.draft_lens[slot] = self.scheduler.seq_lens[slot]
            tok = int(toks[slot])  # rlt: noqa[RLT002] host np after the tick fetch
            self._cur_tokens[slot] = tok
            req = self.scheduler.slots[slot]
            if req is not None and req.adapter is not None:
                self.stats.note_adapter(req.adapter, tokens=1)
            done = self.scheduler.append_token(slot, tok)
            if done:
                self._complete(slot)

    def _spec_tick(self, active: List[int], widths: List[int]) -> None:
        """One draft-propose / target-verify round.

        1. the draft model proposes up to K tokens per slot — K+1
           dispatches of its fixed-width decode program chained on
           device (the first dispatch doubles as the catch-up write for
           slots whose draft cache trails by the bonus token);
        2. the target scores every slot's (current token + drafts)
           window in ONE K+1-wide verify dispatch, sampling its own
           token at each position with the request's position-keyed
           streams;
        3. host-side accept/reject keeps each slot's longest agreeing
           draft prefix plus the target's token at the first
           disagreement (== the bonus token when everything agreed),
           emits that variable-width batch, and rolls both caches back
           to the emitted frontier (blocks past it return to the pool).

        Greedy slots emit exactly the tokens sequential greedy decode
        would: every accepted draft MATCHED the target argmax, and the
        corrected token IS the target argmax at the first mismatch.
        """
        import jax.numpy as jnp

        sched = self.scheduler
        K = self.spec_k
        t0 = time.monotonic()
        limits = np.zeros((self.config.num_slots,), np.int32)
        for slot in active:
            limits[slot] = (  # rlt: noqa[RLT002] host np state
                int(sched.seq_lens[slot]) + widths[slot] + 1
            )
        gaps = np.where(  # rlt: noqa[RLT002] host scheduler arrays
            np.asarray([r is not None for r in sched.slots]),
            sched.seq_lens - sched.draft_lens, 0,
        ).astype(np.int32)
        # Dispatch-0 token: the emitted token AT draft_lens — the
        # bonus-token catch-up write for gap-1 slots, the current token
        # (= proposal seed) for everyone else.
        start = np.zeros((self.config.num_slots,), np.int32)
        for slot in active:
            req = sched.slots[slot]
            if gaps[slot]:
                start[slot] = req.generated[  # rlt: noqa[RLT002] host np state
                    int(sched.draft_lens[slot]) - req.prompt_len
                ]
            else:
                start[slot] = self._cur_tokens[slot]
        cur = jnp.asarray(self._cur_tokens)
        limits_j = jnp.asarray(limits)
        tables = jnp.asarray(sched.block_tables)
        ones = jnp.ones((self.config.num_slots,), bool)
        outs = []
        prev = cur
        for j in range(K + 1):
            if j == 0:
                override, mask = jnp.asarray(start), ones
            elif j == 1:
                override, mask = cur, jnp.asarray(gaps > 0)
            else:
                override, mask = cur, jnp.zeros_like(ones)
            prev, self._draft_pool = self._draft_step_fn(
                self.draft_params, self._draft_pool, tables,
                jnp.asarray(sched.draft_lens + j), prev,
                override, mask, limits_j,
            )
            outs.append(prev)
        self.stats.bump("draft_steps", K + 1)
        outs = np.stack(  # rlt: noqa[RLT002] deliberate: host accept/reject
            [np.asarray(o) for o in outs]
        )  # (K+1, W)

        # Per-slot proposals: the K chain outputs starting at the
        # slot's gap offset.
        window = np.zeros((self.config.num_slots, K + 1), np.int32)
        window[:, 0] = self._cur_tokens
        for slot in active:
            g = int(gaps[slot])  # rlt: noqa[RLT002] host np state
            window[slot, 1: K + 1] = outs[g: g + K, slot]

        ad, ad_ids = self._lora_operands()
        sampled, self._pool = self._verify_fn(
            self.params, self._pool, tables,
            jnp.asarray(sched.seq_lens), jnp.asarray(window),
            limits_j, jnp.asarray(sched.temperatures),
            jnp.asarray(sched.sample_seeds), self._tick_top_ks(),
            ad, ad_ids,
        )
        # rlt: noqa[RLT002] deliberate verify sync
        sampled = np.asarray(sampled)  # (W, K+1)
        self.stats.bump("verify_steps")
        dt = time.monotonic() - t0
        # Same busy-time accounting as the plain decode tick, so the
        # capacity oracle's time budget stays honest on speculative
        # engines too.
        self.stats.bump(  # rlt: noqa[RLT002] host float, no device value
            "decode_us", int(dt * 1e6))

        total_emitted = 0
        for slot in active:
            w = widths[slot]
            drafts = window[slot, 1: w + 1]
            target = sampled[slot, : w + 1]
            accepted = 0
            while accepted < w and drafts[accepted] == target[accepted]:
                accepted += 1
            emit = [int(t) for t in drafts[:accepted]]  # rlt: noqa[RLT002] host np
            emit.append(int(target[accepted]))  # rlt: noqa[RLT002] host np
            seq_was = int(sched.seq_lens[slot])  # rlt: noqa[RLT002] host np state
            draft_was = int(sched.draft_lens[slot])  # rlt: noqa[RLT002] host np state
            n, done = sched.append_tokens(slot, emit)
            new_len = seq_was + n
            # Roll BOTH caches back to the emitted frontier: the target
            # wrote the whole window, the draft chain wrote K+1
            # positions from its own frontier; everything past new_len
            # is rejected garbage whose blocks return to the pool.
            sched.truncate_slot_to(slot, new_len)
            sched.draft_lens[slot] = min(draft_was + K + 1, new_len)
            self._cur_tokens[slot] = emit[n - 1]
            total_emitted += n
            self.stats.note_spec_slot(w, min(accepted, n), n)
            req = sched.slots[slot]
            if req is not None and req.adapter is not None:
                self.stats.note_adapter(req.adapter, tokens=n)
            if done:
                self._complete(slot)
        self.stats.bump("spec_ticks")
        self.stats.note_token_latency(dt, n_tokens=total_emitted)

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Drive the loop synchronously until queue and slots drain."""
        for _ in range(max_steps):
            self.step()
            if not self.scheduler.has_work():
                return
        raise RuntimeError(f"still busy after {max_steps} serve steps")

    def _complete(self, slot: int) -> None:
        if self.prefix_cache is not None:
            # Keep the FINISHED chain resident too — prompt plus every
            # generated token whose KV was actually written (the final
            # sampled token never was: seq_lens stops one short of it).
            # A follow-up turn that extends this conversation claims
            # the whole chain instead of re-prefilling it.
            req = self.scheduler.slots[slot]
            toks = req.prompt + req.generated[:-1]
            n = len(toks) // self.config.block_size
            if n:
                self.prefix_cache.insert(
                    req.adapter, toks, self.scheduler._blocks[slot][:n]
                )
        req = self.scheduler.finish(slot)
        e2e = req.finished_t - req.arrival_t
        self.stats.note_completed(e2e)
        if req.adapter is not None:
            self.stats.note_adapter(req.adapter, completed=1)
        if (self.tracer.enabled and req.trace is not None
                and getattr(req, "_trace_local", False)):
            # Engine-owned traces (no router upstream) anchor their own
            # root span; routed requests' roots live router-side.
            self.tracer.record(
                "request", time.time() - e2e, e2e,
                args=trace_args(req.trace, rid=req.rid,
                                status=req.state.value),
            )
        self._finish_handle(req)

    def _finish_handle(self, req) -> None:
        with self._lock:
            handle = self._handles.pop(req.rid, None)
            self._done_feed.append((req.rid, req.state.value))
        if handle is not None:
            handle._done.set()
        self._reply_done(req)

    # -- multi-tenant LoRA ---------------------------------------------------
    def add_adapter(self, name: str, adapter: dict) -> int:
        """Hot-load (or replace) one tenant's LoRA adapter; returns its
        pool slot.  Replacement of an adapter any queued/active request
        is decoding through is refused loudly — swapping factors under
        a live sequence would change its model mid-stream."""
        if self.adapters is None:
            raise ValueError(
                "engine has no adapter pool — build it with "
                "ServeConfig(max_adapters=N, adapter_rank=r)"
            )
        name = str(name)
        with self._lock:
            # Guard and load under ONE lock hold: a submit landing
            # between them would resolve the name against the factors
            # being replaced (submit resolves slots under this lock).
            if self.adapters.has(name) \
                    and self.scheduler.references_adapter(name):
                raise RuntimeError(
                    f"adapter {name!r} is serving queued/active "
                    f"requests — replacing its factors would change "
                    f"their model mid-stream; drain the tenant first"
                )
            slot = self.adapters.add(name, adapter)
            if self.prefix_cache is not None:
                # Adapter-keyed chains carry adapter-specific KV: a
                # replace means the resident chain no longer matches
                # the factors a future claim would decode through.
                self._prefix_drops.append(name)
        self.stats.bump("adapter_loads")
        return slot

    def remove_adapter(self, name: str) -> None:
        """Free one tenant's pool slot.  Refused while any queued or
        active request references the name (a freed slot re-issued to
        a new tenant would serve the old tenant's requests the NEW
        tenant's delta — the cross-tenant corruption a serving pool
        must never allow)."""
        if self.adapters is None:
            raise ValueError("engine has no adapter pool")
        name = str(name)
        with self._lock:
            if self.scheduler.references_adapter(name):
                raise RuntimeError(
                    f"adapter {name!r} is serving queued/active "
                    f"requests — drain the tenant before removing it"
                )
            self.adapters.remove(name)
            if self.prefix_cache is not None:
                self._prefix_drops.append(name)
        self.stats.bump("adapter_unloads")

    def adapter_names(self) -> List[str]:
        """Loaded tenant names (the replica beat advertises these for
        adapter-aware router placement)."""
        return [] if self.adapters is None else self.adapters.names()

    def drain_done(self) -> List[Tuple[str, str]]:
        """Terminal ``(rid, status)`` pairs since the last call — the
        per-beat completion feed of a disaggregated decode replica
        (``serve/dist/replica.py``): the router prunes its in-flight
        tracking from it, which is what makes failover re-submission
        exact (a request is re-submitted iff no terminal status ever
        reached the router)."""
        with self._lock:
            items = list(self._done_feed)
            self._done_feed.clear()
        return items

    def drain_failed(self) -> List[Tuple[str, str]]:
        """Non-terminal ``(rid, error)`` handoff-admission failures
        since the last call — the beat's ``failed`` feed when
        ``report_handoff_failures`` is on.  The router treats each like
        a prefill-worker failure: re-dispatch the prefill, never a
        terminal client reply."""
        with self._lock:
            items = list(self._failed_feed)
            self._failed_feed.clear()
        return items

    def cancel(self, rid: str) -> bool:
        """Drop one request wherever it is — queued or mid-decode (the
        hedged-request first-winner cancel, and the client-abort path).
        Idempotent: unknown or already-finished rids return False.  The
        terminal status is ``cancelled`` (done feed + typed reply), so
        routers and clients prune it like any completion."""
        with self._lock:
            req = self.scheduler.cancel(rid)
            if req is None:
                return False
            handle = self._handles.pop(rid, None)
            self._done_feed.append((rid, "cancelled"))
        self.stats.bump("cancelled")
        req.finished_t = time.monotonic()
        if handle is not None:
            handle._done.set()
        reply = getattr(req, "_reply", None)
        if reply is not None:
            self._reply(reply, {
                "type": "serve_done", "rid": rid,
                "status": "cancelled", "reason": "cancelled",
                "tokens": [int(t) for t in req.generated],
            })
        return True

    # -- background thread ---------------------------------------------------
    def start(self) -> "ServeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._serve_forever, name="rlt-serve", daemon=True
        )
        self._thread.start()
        return self

    def _serve_forever(self) -> None:
        if self.fault_member is not None:
            # The serve thread declares its fleet identity so
            # replica:-pinned faults fire here, not on whichever member
            # thread registered last (inproc fleets share one process).
            set_member(*self.fault_member)
        while not self._stop.is_set():
            try:
                worked = self.step()
            except Exception as e:  # noqa: BLE001 - a dying loop must
                # fail its pending work loudly, never strand it
                self._fail_pending(e)
                return
            if not worked:
                time.sleep(self.config.idle_wait_s)

    def _fail_pending(self, exc: BaseException) -> None:
        """The serve loop died: mark the engine dead (submit() refuses
        from now on), fail every in-flight/queued handle with the error,
        and tell queue-plane clients (``serve_done(status="error")``)
        instead of letting them block to their timeouts."""
        import logging

        self._error = exc
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
        logging.getLogger(__name__).error(
            "serve loop died: %r — failing %d pending request(s)",
            exc, len(handles), exc_info=exc,
        )
        for handle in handles:
            handle.error = exc
            req = handle.request
            reply = getattr(req, "_reply", None)
            if reply is not None:
                self._reply(reply, {
                    "type": "serve_done", "rid": req.rid,
                    "status": "error", "error": repr(exc),
                    "tokens": [int(t) for t in req.generated],
                })
            handle._done.set()

    def halt_loop(self) -> None:
        """Quiesce the background serve thread WITHOUT tearing the
        engine down (``stop()`` also closes reply handles, the inbox
        and exporters): the planned-drain migration path halts the
        loop, exports the resident sequences from the frozen scheduler
        (:meth:`export_resident`), then calls :meth:`stop`."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def export_resident(self) -> List[dict]:
        """Export every resident decoding sequence's KV blocks plus
        scheduler position — the planned-drain live-migration payload
        (docs/FAULT_TOLERANCE.md "Serving-plane faults").  Call with
        the loop quiesced (:meth:`halt_loop`); each entry feeds
        ``make_migration_item`` and a survivor's migration admission.
        Queued requests and chunked prefills mid-flight are NOT
        exported: they have no emitted position worth moving, so the
        router's ordinary recompute failover covers them."""
        out = []
        sched = self.scheduler
        Bs = self.config.block_size
        for slot, req in enumerate(sched.slots):
            if req is None or slot in self._chunk_jobs:
                continue
            if not req.generated:
                continue
            # seq_lens[slot] == prompt + generated − 1: the final
            # sampled token's KV was never written (it is the NEXT
            # decode tick's input), so exactly ceil(seq_len/Bs) blocks
            # hold everything the survivor needs.
            seq_len = int(sched.seq_lens[slot])
            n_blocks = -(-seq_len // Bs)
            ids = sched._blocks[slot][:n_blocks]
            kv = self.cache.export_blocks(self._pool, ids)
            fields = {
                "rid": req.rid, "prompt": list(req.prompt),
                "max_new_tokens": int(req.max_new_tokens),
                "temperature": float(req.temperature),
                "eos_token_id": req.eos_token_id,
                "top_k": req.top_k,
                "adapter": req.adapter,
                "priority": int(req.priority),
                "sample_seed": req.sample_seed,
            }
            reply = getattr(req, "_reply", None)
            if reply is not None:
                fields["reply"] = list(reply)
            out.append({
                "req": fields,
                "generated": list(req.generated),
                "cur_token": int(self._cur_tokens[slot]),
                "seq_len": seq_len,
                "kv": kv,
            })
        return out

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self.prefix_cache is not None:
            self.prefix_cache.drop_all()
        if self._inbox is not None:
            self._inbox.shutdown()
            self._inbox = None
        with self._lock:
            reply_handles = list(self._reply_handles.values())
            self._reply_handles.clear()
        for h in reply_handles:
            h.close()
        # Final unthrottled export: a recompile or counter bump landing
        # inside the last export_every_s window must still reach the
        # prom file / serve-live.json before teardown.
        self._maybe_export(force=True)
        if self._exporter is not None:
            self._exporter.close()
        if self._trace_dir is not None and self.tracer.events():
            import os

            try:
                os.makedirs(self._trace_dir, exist_ok=True)
                self.tracer.export_jsonl(
                    f"{self._trace_dir}/trace-serve-"
                    f"{self._trace_name}.jsonl"
                )
            except OSError:
                pass  # a full disk must not fail the teardown
        # Serve-replica teardown reclaims dead prefill handoffs: a
        # prefill worker killed -9 mid-handoff leaves rlt-kv segments
        # whose owner pid is gone and which no consumer will ever read
        # — the engine-close sweep (mirroring the router's failover
        # sweep) keeps tmpfs bounded across replica restarts.
        try:
            from ray_lightning_tpu.cluster.shm import sweep_stale_segments

            sweep_stale_segments("rlt-kv")
        except Exception:  # noqa: BLE001 - janitorial, never raises out
            pass

    # -- DriverQueue request plane ------------------------------------------
    def queue_handle(self):
        """Picklable submission handle for :class:`serve.client.
        ServeClient` — created on first use (driver-side TCP inbox)."""
        if self._inbox is None:
            from ray_lightning_tpu.cluster.queue import DriverQueue

            self._inbox = DriverQueue()
        return self._inbox.handle

    def _drain_inbox(self) -> None:
        if self._inbox is None:
            return
        import queue as _pyqueue

        while True:
            try:
                item = self._inbox.get_nowait()
            except _pyqueue.Empty:
                break
            try:
                self._handle_queue_request(item)
            except Exception as e:  # noqa: BLE001 - a bad request must
                # never take the serve loop down
                import logging

                logging.getLogger(__name__).warning(
                    "serve: dropped malformed queue request: %s", e
                )
        if self._deferred_inbox:
            # One retry pass per drain: each item re-defers (bounded)
            # or proceeds now that its adapter-load frame landed above.
            retry, self._deferred_inbox = self._deferred_inbox, deque()
            for item in retry:
                try:
                    self._handle_queue_request(item)
                except Exception as e:  # noqa: BLE001 - as above
                    import logging

                    logging.getLogger(__name__).warning(
                        "serve: dropped malformed queue request: %s", e
                    )

    def _handle_queue_request(self, item: dict) -> None:
        if not isinstance(item, dict):
            raise ValueError(f"not a serve item: {type(item).__name__}")
        kind = item.get("type")
        if kind == "serve_adapter_load":
            # Tenant hot-load from the queue plane (router dispatch or
            # operator tooling): scatter into the pool through the ONE
            # compiled scatter program — a join-on-arrival for MODELS,
            # recompile-free like every other admission.
            self._load_adapter_item(item)
            return
        if kind == "serve_cancel":
            # Hedge loser (or client abort): drop the request wherever
            # it is — queued, decoding, or already gone (idempotent).
            self.cancel(str(item["rid"]))
            return
        if kind in ("serve_kv_handoff", "serve_migration"):
            fields = dict(item["req"])
            adapter = fields.get("adapter")
            if (adapter is not None and self.adapters is not None
                    and not self.adapters.has(str(adapter))):
                # The router's serve_adapter_load frame rides the
                # router->replica lane; the handoff arrives from the
                # prefill WORKER's own connection and can outrun it.
                # Defer on a WALL-CLOCK deadline (a drain-count bound
                # would scale with loop speed: an idle replica drains
                # every ~2ms, exhausting any count long before a
                # chunk-sent multi-MB blob lands cross-host) instead of
                # failing a valid request "unknown adapter" — checked
                # BEFORE _decode_handoff so the read-once shm payload
                # survives the retry.
                deadline = item.get("_adapter_wait_deadline")
                if deadline is None:
                    deadline = time.monotonic() + 10.0
                    item["_adapter_wait_deadline"] = deadline
                if time.monotonic() < deadline:
                    self._deferred_inbox.append(item)
                    return
        elif kind == "serve_request":
            fields = item
        else:
            raise ValueError(f"not a serve request/handoff: {kind!r}")
        rid = str(item["rid"])
        reply = tuple(fields["reply"])  # (host, port)
        if kind == "serve_migration":
            self._admit_migration(item, fields, rid, reply)
            return
        if item.get("hedge"):
            # Hedged duplicate that reached a single engine directly
            # (no router to place it on a SECOND replica): drop it —
            # the primary admission is already decoding this rid, and
            # a duplicate here would double-book the slot.
            with self._lock:
                if rid in self._handles:
                    return

        def on_token(i: int, tok: int) -> None:
            self._reply(reply, {
                "type": "serve_token", "rid": rid, "index": i,
                "token": int(tok),
            })

        try:
            if kind == "serve_kv_handoff":
                _fault_fire("handoff_read", rid=rid,
                            path=item.get("shm"))
            handoff = (self._decode_handoff(item)
                       if kind == "serve_kv_handoff" else None)
            trace_ctx = None
            if self.tracer.enabled:
                from ray_lightning_tpu.telemetry.propagate import (
                    extract, sent_ts,
                )

                # The request body carries the ROUTER-stamped context
                # (the trace root); a handoff envelope additionally
                # carries the prefill worker's span + send time.  The
                # transfer interval is booked HERE — at read — so it
                # ends where queue_wait begins (booking it at admission
                # would fold the slot backlog into "transfer" and
                # double-count it against queue_wait).
                trace_ctx = extract(fields)
                if handoff is not None:
                    h_sent = sent_ts(item)
                    if h_sent is not None and trace_ctx is not None:
                        h_dur = max(0.0, time.time() - h_sent)
                        self.tracer.record(
                            "handoff_transfer", h_sent, h_dur,
                            args=trace_args(
                                child_context(extract(item)
                                              or trace_ctx),
                                rid=rid,
                            ),
                        )
                        self.stats.note_phase("handoff_transfer",
                                              h_dur)
            handle = self.submit(
                fields["prompt"], int(fields["max_new_tokens"]),
                temperature=float(fields.get("temperature", 0.0)),
                eos_token_id=fields.get("eos_token_id"),
                top_k=fields.get("top_k"),
                spec=fields.get("spec"),
                adapter=fields.get("adapter"),
                deadline_s=fields.get("deadline_s"),
                sample_seed=fields.get("sample_seed"),
                on_token=on_token, rid=rid, _handoff=handoff,
                _trace_ctx=trace_ctx,
            )
        except FaultBlackhole:
            # Injected network partition on the read side: the frame
            # just never arrived.  No reply, no feed entry — recovery
            # is the router's beat-loss/claim machinery, exactly as for
            # a real partition.
            return
        except (ValueError, TypeError, KeyError, OSError,
                FaultInjected) as e:
            # TypeError covers malformed field coercion (int(None), ...);
            # KeyError/OSError cover a torn handoff payload or a segment
            # that vanished before the read (TTL-pruned after a very
            # slow handoff, swept by a teardown, or a path from another
            # host): once the reply address is known, every bad request
            # gets the typed "invalid" reply — a silent drop would leave
            # the client blocking to its timeout AND the router counting
            # a phantom in-flight request against this replica forever.
            # The done feed carries the terminal status so a router
            # prunes it like any other.
            if kind == "serve_kv_handoff" and self.report_handoff_failures:
                # Disaggregated replica: a torn/vanished payload is the
                # PREFILL's failure, not the request's — report it on
                # the beat's failed feed so the router re-dispatches
                # the prefill (same recovery as a worker death) instead
                # of failing the client terminally.
                with self._lock:
                    self._failed_feed.append((rid, repr(e)))
                return
            with self._lock:
                self._done_feed.append((rid, "invalid"))
            self._reply(reply, {
                "type": "serve_done", "rid": rid, "status": "invalid",
                "error": str(e), "tokens": [],
            })
            return
        handle.request._reply = reply
        if handle.status == "rejected":
            self._reply_done(handle.request)

    def _load_adapter_item(self, item: dict) -> None:
        """One ``serve_adapter_load`` frame: resolve the chunked-bytes
        / tmpfs-segment payload (same dual transport as KV handoffs)
        and add the tenant.  Raises on pool-less engines or malformed
        payloads — ``_drain_inbox`` logs and drops, and the tenant's
        subsequent requests come back as typed ``invalid`` replies
        ("unknown adapter"), so a failed load is never silent."""
        from ray_lightning_tpu.serve.lora import decode_adapter

        if self.adapters is None:
            raise ValueError(
                "serve_adapter_load on an engine without an adapter "
                "pool (ServeConfig.max_adapters == 0) — router caps "
                "should have excluded this replica"
            )
        _fault_fire("adapter_load", rid=str(item.get("name", "")))
        self.add_adapter(str(item["name"]), decode_adapter(item))

    def _decode_handoff(self, item: dict) -> dict:
        """Decode a ``serve_kv_handoff`` frame's ``{"kv", "logits"}``
        payload (shm segments are read once and unlinked —
        consumer-owned lifetime).  Geometry drift between the prefill
        worker and this replica is a deploy bug and fails the request
        loudly (typed ``invalid`` reply upstream)."""
        # Runtime import (the dist package imports this module at its
        # own import time); decode_kv_payload is the one inverse of the
        # worker's encode_kv_payload — an encoding change lands on both
        # sides or neither.
        from ray_lightning_tpu.serve.dist.handoff import decode_kv_payload

        tree = decode_kv_payload(item)
        bucket = int(item["bucket"])
        n_blocks = int(tree["kv"]["k"].shape[1])
        expect = self.scheduler.bucket_for(int(item["prompt_len"]))
        if bucket != expect or n_blocks * self.config.block_size != bucket:
            raise ValueError(
                f"kv handoff geometry mismatch: worker bucket {bucket} "
                f"({n_blocks} blocks of {self.config.block_size}) vs "
                f"replica bucket {expect} — prefill worker and decode "
                f"replica must share block_size/bucket config"
            )
        return tree

    def _admit_migration(self, item: dict, fields: dict, rid: str,
                         reply: Tuple[str, int]) -> None:
        """One ``serve_migration`` frame: adopt a draining replica's
        resident sequence mid-decode — import its KV blocks, seat the
        request with its emitted history, and continue decode at the
        exact position the source stopped.  Zero recomputed prefill;
        the position-keyed sampler keeps the continued stream
        bitwise-identical at any temperature.  Any adoption failure
        (pool dry, geometry drift, torn payload) falls back to the
        recompute path: a fresh submit with the same fleet seed replays
        the identical stream and the client dedups re-emitted
        indices."""

        def on_token(i: int, tok: int) -> None:
            self._reply(reply, {
                "type": "serve_token", "rid": rid, "index": i,
                "token": int(tok),
            })

        try:
            adopted = self._adopt_migration(item, fields, rid, reply,
                                            on_token)
        except (ValueError, TypeError, KeyError, OSError,
                FaultInjected) as e:
            import logging

            logging.getLogger(__name__).warning(
                "serve: migration adopt failed for %s (%s) — "
                "recompute fallback", rid, e,
            )
            adopted = False
        if adopted:
            self.stats.bump("migrations_in")
            return
        self.stats.bump("migration_fallbacks")
        try:
            handle = self.submit(
                fields["prompt"], int(fields["max_new_tokens"]),
                temperature=float(fields.get("temperature", 0.0)),
                eos_token_id=fields.get("eos_token_id"),
                top_k=fields.get("top_k"),
                adapter=fields.get("adapter"),
                sample_seed=fields.get("sample_seed"),
                on_token=on_token, rid=rid,
            )
        except (ValueError, TypeError, KeyError, OSError) as e:
            with self._lock:
                self._done_feed.append((rid, "invalid"))
            self._reply(reply, {
                "type": "serve_done", "rid": rid, "status": "invalid",
                "error": str(e), "tokens": [],
            })
            return
        handle.request._reply = reply
        if handle.status == "rejected":
            self._reply_done(handle.request)

    def _adopt_migration(self, item: dict, fields: dict, rid: str,
                         reply: Tuple[str, int], on_token) -> bool:
        """Seat one migrated sequence.  True = adopted (decode resumes
        at ``seq_len`` next tick); False = resources unavailable (no
        free slot / pool dry / no matching import width) — the caller
        falls back to recompute.  Malformed payloads raise and fall
        back the same way."""
        import jax.numpy as jnp

        from ray_lightning_tpu.serve.dist.handoff import decode_kv_payload
        from ray_lightning_tpu.serve.scheduler import Request

        sched = self.scheduler
        Bs = self.config.block_size
        prompt = [int(t) for t in fields["prompt"]]
        generated = [int(t) for t in item["generated"]]
        max_new = int(fields["max_new_tokens"])
        seq_len = int(item["seq_len"])
        cur_token = int(item["cur_token"])
        if not generated or len(generated) >= max_new:
            raise ValueError(
                "migration carries no live decode position"
            )
        if seq_len != len(prompt) + len(generated) - 1:
            raise ValueError(
                f"migration position mismatch: seq_len {seq_len} != "
                f"prompt {len(prompt)} + generated {len(generated)} - 1"
            )
        if len(prompt) + max_new > self.max_model_len:
            raise ValueError(
                f"migrated request exceeds max_model_len "
                f"({self.max_model_len})"
            )
        sample_seed = fields.get("sample_seed")
        if sample_seed is None:
            raise ValueError(
                "migration without a sample_seed — the continued "
                "stream would not replay the source's"
            )
        n_blocks = -(-seq_len // Bs)
        kv = decode_kv_payload(item)["kv"]
        if int(kv["k"].shape[1]) != n_blocks:
            raise ValueError(
                f"migration payload carries {int(kv['k'].shape[1])} "
                f"blocks, position {seq_len} needs {n_blocks} — "
                f"source and survivor must share block_size"
            )
        ids = sched._alloc(n_blocks)
        if ids is None:
            return False
        ok = False
        try:
            # Scatter through the SAME per-block-count executables the
            # bucketed handoff imports compiled (greedy decomposition
            # into bucket block counts) — a migration admission never
            # adds a program variant, so steady-state recompiles stay
            # pinned at zero on the survivor.
            sizes = sorted({b // Bs for b in sched.buckets},
                           reverse=True)
            off = 0
            while off < n_blocks:
                c = next((s for s in sizes if s <= n_blocks - off),
                         None)
                if c is None:
                    return False  # bucket set can't tile the remainder
                chunk = jnp.asarray(
                    np.asarray(ids[off: off + c], np.int32)
                )
                payload = {k: jnp.asarray(v[:, off: off + c])
                           for k, v in kv.items()}
                self._pool = self._import_fn(self._pool, payload, chunk)
                off += c
            req = Request(
                rid=rid, prompt=prompt, max_new_tokens=max_new,
                temperature=float(fields.get("temperature", 0.0)),
                eos_token_id=fields.get("eos_token_id"),
                top_k=fields.get("top_k"),
                # The draft cache never saw this prefix: plain decode
                # only.  _spec_tick at width 0 emits exactly the plain
                # position-keyed token, so mixed ticks stay bitwise.
                spec=0,
                adapter=fields.get("adapter"),
                priority=int(fields.get("priority", 0)),
                sample_seed=int(sample_seed),
                on_token=on_token,
            )
            req.generated = generated
            handle = ServeHandle(rid, req)
            with self._lock:
                if req.adapter is not None:
                    if self.adapters is None:
                        raise ValueError(
                            f"migrated request names adapter "
                            f"{req.adapter!r} but this engine has no "
                            f"adapter pool"
                        )
                    try:
                        req._adapter_slot = self.adapters.slot_of(
                            req.adapter
                        )
                    except KeyError:
                        raise ValueError(
                            f"unknown adapter {req.adapter!r} on the "
                            f"migration survivor"
                        ) from None
                slot = sched.adopt(req, ids, seq_len)
                if slot is None:
                    return False
                self.stats.bump("submitted")
                self._handles[rid] = handle
            self._cur_tokens[slot] = cur_token
            req._reply = reply
            ok = True
            return True
        finally:
            if not ok:
                sched.allocator.free(ids)

    def _reply_done(self, req) -> None:
        reply = getattr(req, "_reply", None)
        if reply is None:
            return
        self._reply(reply, {
            "type": "serve_done", "rid": req.rid,
            "status": req.state.value,
            "reason": req.done_reason,
            "tokens": [int(t) for t in req.generated],
        })

    def _reply(self, addr: Tuple[str, int], item: dict) -> None:
        from ray_lightning_tpu.cluster.queue import QueueHandle

        with self._lock:
            handle = self._reply_handles.get(addr)
            if handle is None:
                handle = QueueHandle(addr[0], addr[1])
                self._reply_handles[addr] = handle
        try:
            handle.put(item)
        except (OSError, ConnectionError):
            # Client went away: drop its stream, keep serving others.
            with self._lock:
                self._reply_handles.pop(addr, None)

    # -- telemetry -----------------------------------------------------------
    def _refresh_gauges(self) -> None:
        gauges = self.scheduler.snapshot()
        if self.adapters is not None:
            pool = self.adapters.snapshot()
            gauges["lora_adapters_loaded"] = pool["loaded"]
            gauges["lora_slots_free"] = pool["slots_free"]
            counts = [t for t in
                      self.stats.adapter_token_counts().values() if t]
            # Fairness spread: min/max lifetime tokens across tenants
            # with traffic (1.0 = perfectly fair; the DRR grant policy
            # keeps this near 1 under uniform per-tenant load).
            gauges["lora_fairness_spread"] = (
                min(counts) / max(counts) if len(counts) > 1 else 1.0
            )
        if self.prefix_cache is not None:
            ps = self.prefix_cache.stats()
            hit_rate = (ps["hits"] / ps["lookups"]) if ps["lookups"] \
                else 0.0
            gauges["prefix_cache_hit_rate"] = hit_rate
            gauges["prefix_cached_blocks"] = ps["cached_blocks"]
            self.stats.set_prefix(
                hit_rate=hit_rate, lookups=ps["lookups"],
                hits=ps["hits"],
                blocks_claimed=ps["blocks_claimed"],
                blocks_inserted=ps["blocks_inserted"],
                blocks_evicted=ps["blocks_evicted"],
                cached_blocks=ps["cached_blocks"],
            )
        if self.spec_k > 0:
            counters = self.stats.counters
            drafted = counters.get("spec_drafted", 0)
            gauges["spec_acceptance_rate"] = (
                counters.get("spec_accepted", 0) / drafted if drafted
                else 0.0
            )
            elapsed = max(time.monotonic() - self._started_t, 1e-9)
            # Goodput = EMITTED tokens/s — what clients actually see,
            # vs the drafted+verified work the chip performed.
            gauges["spec_goodput_tokens_per_sec"] = (
                counters.get("spec_emitted", 0) / elapsed
            )
        self.stats.set_gauges(**gauges)

    @property
    def capacity_oracle(self):
        """The headroom oracle (``serve/capacity.py``) when the
        capacity plane is on, else None."""
        return self._capacity

    @property
    def slo_evaluator(self):
        """The burn-rate evaluator (``telemetry/slo.py``) when the SLO
        plane is on, else None."""
        return self._slo

    @property
    def slo_alerts(self) -> List[dict]:
        """Fired ``slo_alert`` events (bounded ring, newest last)."""
        return list(self._slo_alerts)

    def snapshot(self) -> dict:
        """The live serve snapshot (schema:
        ``telemetry/schema.py::validate_serve_snapshot``).  On
        capacity-plane engines the newest headroom-oracle block rides
        the ``capacity`` key — beats built from this snapshot carry it
        to the router for free."""
        snap = self.stats.snapshot()
        if self._capacity is not None and self._capacity.last is not None:
            snap["capacity"] = dict(self._capacity.last)
        return snap

    def _maybe_export(self, force: bool = False) -> None:
        if self._exporter is None and self._live_path is None \
                and self._capacity is None:
            return
        now = time.monotonic()
        if not force and now - self._last_export < self.config.export_every_s:
            return
        self._last_export = now
        if self._capacity is not None:
            # The SLO/capacity plane ticks here, on the CHEAP stats
            # slice (counters + gauges + recent queue-wait p50) — the
            # full snapshot sorts four 4096-sample reservoirs, too
            # heavy for a sub-second tick under the plane's <2%
            # overhead budget.  Recompiles ride the compile-event
            # counter, NOT a ledger snapshot (which walks every
            # program's cost rows).
            from ray_lightning_tpu.telemetry import compile_event_count

            self._capacity.observe(
                self.stats.capacity_view(),
                recompiles=int(compile_event_count()),
            )
            if force or now - self._last_capacity >= self._capacity_every_s:
                self._last_capacity = now
                self._capacity.snapshot()  # caches on .last
        if self._slo is not None:
            fired = self._slo.evaluate()
            if fired:
                self.stats.bump("slo_alerts", len(fired))
        if self._exporter is None and self._live_path is None:
            return
        snap = self.stats.snapshot()
        if self._capacity is not None and self._capacity.last is not None:
            snap["capacity"] = dict(self._capacity.last)
        # The program ledger rides every real export: rlt_program_*
        # gauges on the prom side, the programs pane on the rlt_top
        # side.
        from ray_lightning_tpu.telemetry import program_ledger

        payload = {"serve": snap, "programs": program_ledger.snapshot()}
        if self._slo is not None:
            payload["slo"] = self._slo.snapshot()
        if self._exporter is not None:
            self._exporter.update(payload)
        if self._live_path is not None:
            import json
            import os

            tmp = self._live_path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"ts": snap["ts"], **payload}, f)
                os.replace(tmp, self._live_path)
            except OSError:
                pass  # a full disk must not take the serve loop down
