"""Serving headroom oracle: how much load fits before saturation.

The fleet-scheduler sensing layer for the serve plane (ROADMAP item
4; Gemma-on-TPU frames TPU serving economics as capacity-per-chip,
Podracer wins utilization with continuous sizing — both need this
trend/headroom layer).  One :class:`CapacityOracle` per engine feeds
a :class:`TimeSeriesStore` from every ``ServeStats`` snapshot the
export tick produces, then derives:

- **tick-cost model** — per-bin (busy slots, decode-tick µs) pairs
  from the engine's ``decode_steps``/``decode_us`` counters, fitted
  as ``tick_us = c + h·busy``: host-side per-token work makes the
  tick cost GROW with occupancy, so a constant per-slot rate
  extrapolated from light load overshoots the knee.  Engines that
  don't feed tick counters fall back to tokens/s over sampled mean
  busy slots.
- **capacity / headroom** — ``num_slots`` tokens per full-width tick
  over the modelled full-width tick cost is the saturation
  throughput; headroom is what's left above current load.
- **saturation prediction** — ``predict_saturation_rps(max_new)``
  balances the engine-time budget (one measured admission cost plus
  ``max_new−1`` full-width tick shares per request) into a
  request-rate knee, gated against the measured Poisson-sweep knee
  in bench_serve's ``slo`` block (±20%).
- **KV-exhaustion ETA** — the free-block trend extrapolated to zero.
- **queue-wait slope / rejection rate** — leading indicators the
  burn-rate alerts and the router's headroom tie-break consume.

Snapshots are schema-shaped ``capacity_snapshot`` dicts
(``telemetry/schema.py::validate_capacity_snapshot``) riding the
serve snapshot's optional ``capacity`` block — so beats carry them to
the router for free, and ``aggregate_fleet`` folds per-replica blocks
into the fleet-wide view in ``router-live.json``.  jax-free; clock
injectable per RLT004.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ray_lightning_tpu.telemetry.timeseries import TimeSeriesStore

__all__ = ["CapacityOracle", "aggregate_fleet"]


class CapacityOracle:
    """Per-engine headroom oracle over a bounded time-series store."""

    def __init__(self, interval_s: float = 1.0, window_s: float = 30.0,
                 capacity: int = 600,
                 clock: Optional[Callable[[], float]] = None,
                 store: Optional[TimeSeriesStore] = None):
        self.store = store if store is not None else TimeSeriesStore(
            interval_s=interval_s, capacity=capacity, clock=clock,
        )
        self.window_s = float(window_s)
        import time

        self._clock = clock if clock is not None else time.time
        self.last: Optional[dict] = None  # newest snapshot() result
        self._model: Optional[dict] = None  # newest tick-cost fit

    # -- ingestion -----------------------------------------------------------
    def observe(self, snap: dict, recompiles: Optional[int] = None,
                ts: Optional[float] = None) -> None:
        """Feed one ``ServeStats`` snapshot (and optionally the
        program-ledger recompile total) into the store."""
        if ts is None:
            ts = snap.get("ts", self._clock())
        counters = snap.get("counters", {})
        for name in ("tokens_out", "completed", "submitted",
                     "rejected", "preempted", "admitted",
                     "decode_steps", "decode_us", "admit_us"):
            self.store.observe(name, counters.get(name, 0),
                               kind="counter", ts=ts)
        gauges = snap.get("gauges", {})
        for name in ("blocks_free", "queue_depth", "slots_active"):
            if name in gauges:
                self.store.observe(name, gauges[name], kind="gauge",
                                   ts=ts)
        for name in ("num_slots", "num_blocks"):
            if name in gauges:
                self.store.observe(name, gauges[name], kind="gauge",
                                   ts=ts)
        wait = snap.get("latency", {}).get("queue_wait", {})
        if wait.get("n"):
            self.store.observe("queue_wait_p50_ms", wait["p50_ms"],
                               kind="gauge", ts=ts)
        if recompiles is not None:
            self.store.observe("recompiles", recompiles,
                               kind="counter", ts=ts)

    # -- the oracle ----------------------------------------------------------
    def _tick_model(self, window_s: float) -> Optional[dict]:
        """Affine decode-tick cost over the window's bins:
        ``tick_us = c + h * busy`` fitted by least squares on per-bin
        counter deltas, plus the mean per-admission cost.  ``None``
        until the engine has fed enough tick counters — synthetic
        stores and pre-plane snapshots fall back to the sampled-gauge
        service estimate in :meth:`snapshot`."""
        names = ("decode_steps", "decode_us", "tokens_out",
                 "admitted", "admit_us")
        grid: dict = {}
        for name in names:
            for ts, v in self.store.series(name, window_s):
                grid.setdefault(ts, {})[name] = v
        rows = [grid[ts] for ts in sorted(grid)
                if len(grid[ts]) == len(names)]
        pairs = []          # (busy slots, tick µs) per bin
        admit_costs = []    # per-bin µs per admission
        admitted = 0.0
        for prev, row in zip(rows, rows[1:]):
            d = {k: row[k] - prev[k] for k in names}
            if any(v < 0 for v in d.values()):
                continue    # counter reset mid-window
            if d["decode_steps"] > 0 and d["decode_us"] > 0:
                # First tokens land at admission, not on decode ticks.
                busy = (d["tokens_out"] - d["admitted"]) \
                    / d["decode_steps"]
                if busy > 0:
                    pairs.append(
                        (busy, d["decode_us"] / d["decode_steps"])
                    )
            if d["admitted"] > 0 and d["admit_us"] > 0:
                admitted += d["admitted"]
                admit_costs.append(d["admit_us"] / d["admitted"])
        if len(pairs) < 4 or admitted <= 0:
            return None
        # Robust estimators throughout — a transient host-load burst
        # poisons a handful of bins, and a mean-based fit would carry
        # that straight into the predicted knee.
        n = len(pairs)
        spread = max(b for b, _ in pairs) - min(b for b, _ in pairs)
        h = 0.0
        if spread >= 1.0:
            # Theil–Sen: median of pairwise slopes across bins with
            # real occupancy separation.  A saturated window (every
            # bin full-width) degrades to the median tick cost below.
            slopes = []
            for i in range(n):
                b_i, t_i = pairs[i]
                for j in range(i + 1, n):
                    b_j, t_j = pairs[j]
                    if abs(b_j - b_i) >= 0.5:
                        slopes.append((t_j - t_i) / (b_j - b_i))
            if len(slopes) >= 8:
                slopes.sort()
                h = max(slopes[len(slopes) // 2], 0.0)
        residuals = sorted(t - h * b for b, t in pairs)
        c = max(residuals[n // 2], 0.0)
        if c <= 0.0 and h <= 0.0:
            return None
        admit_costs.sort()
        admit_us = admit_costs[len(admit_costs) // 2]
        return {"c_us": c, "h_us": h,
                "admit_s": admit_us / 1e6, "bins": n}

    def snapshot(self, window_s: Optional[float] = None) -> dict:
        """One schema-shaped ``capacity_snapshot``; cached on
        ``self.last`` so ``ServeEngine.snapshot()`` (and therefore
        every beat) attaches it without recomputing."""
        w = window_s if window_s is not None else self.window_s
        store = self.store
        tokens_per_s = store.rate("tokens_out", w) or 0.0
        num_slots = store.last("num_slots") or 0.0
        model = self._tick_model(w)
        self._model = model
        service = None
        capacity_tps = None
        if model is not None and num_slots > 0:
            # Roofline from measured phase costs: a full-width tick
            # costs c + h·S µs and lands S tokens.
            t_full = (model["c_us"] + model["h_us"] * num_slots) / 1e6
            if t_full > 0:
                capacity_tps = num_slots / t_full
                service = capacity_tps / num_slots
        if capacity_tps is None:
            busy = store.mean("slots_active", w)
            if busy is not None and busy > 0 and tokens_per_s > 0:
                service = tokens_per_s / busy
            capacity_tps = service * num_slots if service else None
        headroom = None
        utilization = None
        if capacity_tps:
            headroom = max(capacity_tps - tokens_per_s, 0.0)
            utilization = min(max(tokens_per_s / capacity_tps, 0.0), 1.0)
        submitted = store.rate("submitted", w)
        rejected = store.rate("rejected", w)
        rejection_rate = 0.0
        if submitted and submitted > 0:
            rejection_rate = min(max((rejected or 0.0) / submitted,
                                     0.0), 1.0)
        eta = store.eta_to("blocks_free", 0.0, w)
        if eta is not None and eta < 0:
            eta = None  # already past the threshold bin — not a trend
        snap = {
            "type": "capacity_snapshot",
            "ts": self._clock(),
            "window_s": w,
            "tokens_per_s": tokens_per_s,
            "service_rate_per_slot": service,
            "capacity_tokens_per_s": capacity_tps,
            "headroom_tokens_per_s": headroom,
            "utilization": utilization,
            "kv_exhaustion_eta_s": eta,
            "queue_wait_slope_ms_per_s": store.slope(
                "queue_wait_p50_ms", w
            ),
            "queue_depth": store.last("queue_depth") or 0.0,
            "rejection_rate": rejection_rate,
        }
        self.last = snap
        return snap

    def predict_saturation_rps(self, max_new_tokens: int,
                               window_s: Optional[float] = None
                               ) -> Optional[float]:
        """The request-rate knee.  With a tick-cost fit: balance the
        engine-time budget — every request charges one measured
        admission (prefill dispatch + TTFT sync) plus its share of
        ``max_new−1`` full-width decode ticks.  Without one: token
        capacity over tokens per request.  ``None`` until the oracle
        has measured enough — it refuses to guess before it has
        data."""
        snap = self.snapshot(window_s)
        capacity_tps = snap["capacity_tokens_per_s"]
        if not capacity_tps or max_new_tokens < 1:
            return None
        model = self._model
        num_slots = self.store.last("num_slots") or 0.0
        if model is not None and num_slots > 0:
            tick_s = (model["c_us"] + model["h_us"] * num_slots) / 1e6
            per_req = model["admit_s"] + \
                max(max_new_tokens - 1, 0) * tick_s / num_slots
            if per_req > 0:
                return 1.0 / per_req
        return capacity_tps / max_new_tokens


def aggregate_fleet(blocks: List[Optional[dict]]) -> Optional[dict]:
    """Fold per-replica ``capacity_snapshot`` blocks into the
    fleet-wide view the router exports: throughput and capacity sum;
    utilization is load-weighted; the ETA is the fleet's WORST (the
    first replica to exhaust KV is the fleet event)."""
    live = [b for b in blocks if isinstance(b, dict)]
    if not live:
        return None
    tokens = sum(b.get("tokens_per_s") or 0.0 for b in live)
    caps = [b.get("capacity_tokens_per_s") for b in live]
    capacity = sum(c for c in caps if c) or None
    etas = [b.get("kv_exhaustion_eta_s") for b in live]
    etas = [e for e in etas if isinstance(e, (int, float))]
    headroom = max(capacity - tokens, 0.0) if capacity else None
    utilization = None
    if capacity:
        utilization = min(max(tokens / capacity, 0.0), 1.0)
    return {
        "replicas_reporting": len(live),
        "tokens_per_s": tokens,
        "capacity_tokens_per_s": capacity,
        "headroom_tokens_per_s": headroom,
        "utilization": utilization,
        "kv_exhaustion_eta_s": min(etas) if etas else None,
    }
