"""SLO stats for the serving plane: TTFT, token latency, occupancy.

jax-free so the bench, the schema gate and the exporters can use it
without a backend.  Latency families are bounded reservoirs (newest-N):
a serving process runs for days; unbounded lists would be a slow leak,
and SLO percentiles over the recent window are what an operator acts
on anyway.

Snapshot schema is pinned in ``telemetry/schema.py``
(``validate_serve_snapshot``) and self-tested by
``tools/check_telemetry_schema.py`` — ``rlt_top`` and the OpenMetrics
exporter parse these dicts long after this producer moves on.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["ServeStats", "percentile"]

# Newest-N window per latency family.  4096 tokens at serving rates is
# minutes of traffic — enough for a stable p99, small enough to forget.
_RESERVOIR = 4096

_COUNTER_KEYS = (
    "submitted", "admitted", "completed", "rejected", "expired",
    "preempted", "tokens_out", "prefills", "decode_steps",
)


def percentile(values: List[float], p: float) -> Optional[float]:
    """Nearest-rank percentile (``p`` in [0, 100]); None on empty."""
    if not values:
        return None
    vals = sorted(values)
    k = max(0, min(len(vals) - 1, int(round(p / 100.0 * len(vals))) - 1))
    if p <= 0:
        k = 0
    return vals[k]


class _Reservoir:
    __slots__ = ("_vals", "_n", "_cap")

    def __init__(self, cap: int = _RESERVOIR):
        self._vals: List[float] = []
        self._n = 0
        self._cap = cap

    def add(self, v: float) -> None:
        self._n += 1
        self._vals.append(v)
        if len(self._vals) > self._cap:
            del self._vals[: len(self._vals) - self._cap]

    def summary_ms(self) -> Optional[Dict[str, float]]:
        if not self._vals:
            return None
        return {
            "n": self._n,
            "p50_ms": round(percentile(self._vals, 50) * 1e3, 3),
            "p99_ms": round(percentile(self._vals, 99) * 1e3, 3),
            "max_ms": round(max(self._vals) * 1e3, 3),
        }

    def phase_summary_ms(self) -> Optional[Dict[str, float]]:
        """The per-phase decomposition spelling (p50/p95 — critical-path
        phases are budget lines, and a p99 over a 4096 window is mostly
        noise for the short ones)."""
        if not self._vals:
            return None
        return {
            "n": self._n,
            "p50_ms": round(percentile(self._vals, 50) * 1e3, 3),
            "p95_ms": round(percentile(self._vals, 95) * 1e3, 3),
        }


class ServeStats:
    """Thread-safe counters + latency reservoirs + gauges.

    Engine-fed: the serve loop calls the ``note_*`` hooks; any thread
    (exporter refresh, bench assertions) may snapshot concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in _COUNTER_KEYS}
        self._ttft = _Reservoir()
        self._token = _Reservoir()       # inter-token latency, steady decode
        self._queue_wait = _Reservoir()  # arrival → admission
        self._e2e = _Reservoir()         # arrival → finished
        # Critical-path phase reservoirs (queue_wait, prefill_compute,
        # handoff_transfer, decode_admission, first_token, ...) —
        # lazily created by note_phase so engines that never trace keep
        # snapshots byte-identical to pre-tracing rounds.
        self._phases: Dict[str, _Reservoir] = {}
        # Per-adapter (tenant) accounting — lazily created by
        # note_adapter, so engines without an adapter pool keep
        # snapshots byte-identical to pre-LoRA rounds.  The bench's
        # fairness spread and the rlt_top tenant pane read these.
        self._adapters: Dict[str, Dict[str, int]] = {}
        # Prefix-cache block — lazily set by set_prefix, so engines
        # without the cache keep snapshots byte-identical to pre-cache
        # rounds (same contract as phases/adapters above).
        self._prefix: Optional[Dict[str, float]] = None
        self.gauges: Dict[str, float] = {}

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def note_admitted(self, wait_s: float) -> None:
        with self._lock:
            self.counters["admitted"] += 1
            self._queue_wait.add(wait_s)

    def note_first_token(self, ttft_s: float) -> None:
        with self._lock:
            self._ttft.add(ttft_s)

    def note_token_latency(self, dt_s: float, n_tokens: int = 1) -> None:
        """One decode-step wall interval, attributed to each of the
        ``n_tokens`` landed in it (they shared the step)."""
        with self._lock:
            self.counters["tokens_out"] += n_tokens
            for _ in range(n_tokens):
                self._token.add(dt_s)

    def note_completed(self, e2e_s: float) -> None:
        with self._lock:
            self.counters["completed"] += 1
            self._e2e.add(e2e_s)

    def note_spec_slot(self, drafted: int, accepted: int,
                       emitted: int) -> None:
        """One slot's accounting for one speculative verify tick.
        Spec counters exist only on engines that actually speculate
        (lazily created), so plain engines' snapshots — and their
        OpenMetrics render — stay byte-identical to pre-spec rounds."""
        if accepted > drafted:
            raise ValueError(
                f"spec accounting bug: accepted {accepted} > drafted "
                f"{drafted}"
            )
        with self._lock:
            for key, n in (("spec_drafted", drafted),
                           ("spec_accepted", accepted),
                           ("spec_emitted", emitted)):
                self.counters[key] = self.counters.get(key, 0) + n

    def note_adapter(self, name: str, tokens: int = 0,
                     completed: int = 0) -> None:
        """Per-tenant accounting for one emission/completion on a
        multi-LoRA engine (``serve/lora.py``) — the fairness surface:
        spread across these token counters is what the
        deficit-round-robin grant policy bounds."""
        with self._lock:
            entry = self._adapters.get(name)
            if entry is None:
                entry = self._adapters[name] = {
                    "tokens_out": 0, "completed": 0,
                }
            entry["tokens_out"] += tokens
            entry["completed"] += completed

    def note_phase(self, phase: str, dur_s: float) -> None:
        """One critical-path phase interval for one request (the
        tracing plane feeds these; see docs/OBSERVABILITY.md
        "Distributed tracing" for the phase definitions)."""
        with self._lock:
            res = self._phases.get(phase)
            if res is None:
                res = self._phases[phase] = _Reservoir()
            res.add(dur_s)

    def adapter_token_counts(self) -> Dict[str, int]:
        """Lifetime emitted tokens per adapter — the engine's fairness
        gauge (min/max spread) reads this each tick."""
        with self._lock:
            return {k: v["tokens_out"] for k, v in self._adapters.items()}

    def set_gauges(self, **gauges: float) -> None:
        with self._lock:
            self.gauges.update(gauges)

    def set_prefix(self, **fields: float) -> None:
        """Replace the prefix-cache block (engine-fed each gauge
        refresh from ``PrefixIndex.stats()``; schema:
        ``telemetry/schema.py`` ``prefix`` block)."""
        with self._lock:
            self._prefix = dict(fields)

    # -- consumption ---------------------------------------------------------
    def capacity_view(self) -> Dict[str, object]:
        """The cheap per-tick slice the SLO/capacity plane ingests:
        counters + gauges + a recent queue-wait p50.  ``snapshot()``
        sorts every 4096-sample reservoir — fine at human export
        cadence, too heavy for the plane's sub-second tick (which
        must stay under its 2% serve-loop overhead budget)."""
        with self._lock:
            out: Dict[str, object] = {
                "ts": time.time(),
                "counters": dict(self.counters),
                "gauges": {k: float(v) for k, v in self.gauges.items()},
            }
            recent = self._queue_wait._vals[-512:]
        p50 = percentile(recent, 50)
        out["latency"] = {} if p50 is None else {
            "queue_wait": {"n": len(recent),
                           "p50_ms": round(p50 * 1e3, 3)},
        }
        return out

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {
                "ts": time.time(),
                "counters": dict(self.counters),
                "gauges": {k: float(v) for k, v in self.gauges.items()},
            }
            latency = {}
            for name, res in (("ttft", self._ttft),
                              ("token", self._token),
                              ("queue_wait", self._queue_wait),
                              ("e2e", self._e2e)):
                s = res.summary_ms()
                if s is not None:
                    latency[name] = s
            out["latency"] = latency
            if self._phases:  # tracing engines only — see __init__
                phases = {}
                for name, res in self._phases.items():
                    s = res.phase_summary_ms()
                    if s is not None:
                        phases[name] = s
                out["phases"] = phases
            if self._adapters:  # multi-LoRA engines only — see __init__
                out["adapters"] = {
                    name: dict(entry)
                    for name, entry in self._adapters.items()
                }
            if self._prefix is not None:  # prefix-cache engines only
                out["prefix"] = dict(self._prefix)
            return out
