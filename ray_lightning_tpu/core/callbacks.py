"""Callback system — hooks firing inside the worker-side fit loop.

≙ Lightning callbacks as the reference uses them: callbacks travel pickled
with the trainer to workers and fire deep inside the remote fit loop
(reference ships ``TuneReportCallback`` this way, ``tune.py:59-134``; tests
assert sampler/device placement via callbacks, ``test_ddp.py:179-211``).
The ``trainer`` argument every hook receives is the **worker-side loop
context** (:class:`ray_lightning_tpu.core.loop.LoopContext`) — a duck-typed
subset of the driver Trainer (rank, metrics, state, should_stop).

Rank-zero file I/O discipline: on a multi-host mesh all hosts run the same
loop; only ``trainer.is_global_zero`` writes checkpoints (the reference
gets this from Lightning's rank_zero machinery, ``ray_ddp.py:420``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_lightning_tpu.fault.drain import sync_point_crossed

__all__ = [
    "Callback",
    "ModelCheckpoint",
    "EarlyStopping",
    "CSVLogger",
    "StochasticWeightAveraging",
    "ExponentialMovingAverage",
    "DeviceStatsCallback",
    "ProfilerCallback",
    "TelemetryCallback",
]


class Callback:
    """Base callback: override any subset of hooks."""

    def setup(self, trainer, module, stage: str) -> None: ...

    def on_fit_start(self, trainer, module) -> None: ...

    def on_train_epoch_start(self, trainer, module) -> None: ...

    def on_train_batch_end(
        self, trainer, module, logs: Dict[str, float], batch_idx: int
    ) -> None:
        """End of a train dispatch.  Cadence contract: on the per-step
        path this fires once per micro-batch.  Under **megastep**
        execution (``megastep=K`` — docs/PERFORMANCE.md) it fires once
        per K-step STRIDE: ``trainer.micro_step``/``global_step`` have
        already advanced across the whole stride, ``logs`` carries the
        stride's FINAL inner step's values, and ``batch_idx`` is the
        stride's last batch index.  Count steps from the trainer's
        counters, never from call counts; step-cadence callbacks (EMA)
        must compound over ``global_step`` deltas."""

    def on_accumulation_flush(
        self, trainer, module, logs: Dict[str, float], batch_idx: int
    ) -> None:
        """The epoch-end partial-accumulation flush completed one extra
        OPTIMIZER step (``trainer.global_step`` already advanced) without
        a new micro-batch.  Default: no-op — re-broadcasting
        ``on_train_batch_end`` here would double-fire side-effecting
        batch-cadence callbacks (CSV rows, tune reports) on an event
        they already observed.  Step-cadence callbacks (EMA) override
        this to observe the flushed update."""

    def on_train_epoch_end(self, trainer, module) -> None: ...

    def on_validation_epoch_end(self, trainer, module) -> None: ...

    def on_fit_end(self, trainer, module) -> None: ...

    def teardown(self, trainer, module, stage: str) -> None: ...

    def state_dict(self) -> Dict[str, Any]:
        return {}

    def load_state_dict(self, state: Dict[str, Any]) -> None: ...


class ModelCheckpoint(Callback):
    """Save state streams to disk, tracking the best by a monitored metric.

    ≙ Lightning's ``ModelCheckpoint`` as the reference relies on it:
    writes happen on workers, and worker-0's ``best_model_path`` is adopted
    by the driver post-fit (reference ``ray_ddp.py:393-395``).  Checkpoints
    are topology-independent state streams (host-gathered pytrees), so a
    run may resume with a different worker count
    (≙ ``test_ddp_sharded.py:119-138``).
    """

    def __init__(
        self,
        dirpath: Optional[str] = None,
        filename: str = "epoch={epoch}-step={step}",
        monitor: Optional[str] = None,
        mode: str = "min",
        save_top_k: int = 1,
        every_n_epochs: int = 1,
        async_write: bool = False,
        verify: bool = False,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.dirpath = dirpath
        self.filename = filename
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.every_n_epochs = every_n_epochs
        # async_write: serialization + disk IO happen on a background
        # writer thread (the gather stays collective/synchronous); the
        # fit joins pending writes at fit end, and pruning flushes
        # before deleting so it never races an in-flight write.
        self.async_write = async_write
        # verify: read each written checkpoint back and check its crc
        # frame (utils/state_stream.py) — catches a lying disk at write
        # time, when the in-memory state still exists to re-save, rather
        # than at the resume that needed it.  Costs a full file read per
        # save; sync writes verify immediately, async ones at fit end.
        self.verify = verify
        self.best_model_path: str = ""
        self.best_model_score: Optional[float] = None
        self._saved: list = []  # [(score, path)]

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir, "checkpoints")

    def _score(self, metrics: Dict[str, float]) -> Optional[float]:
        if self.monitor is None:
            return None
        value = metrics.get(self.monitor)
        return None if value is None else float(value)

    def _is_better(self, score: float) -> bool:
        if self.best_model_score is None:
            return True
        return (
            score < self.best_model_score
            if self.mode == "min"
            else score > self.best_model_score
        )

    def on_train_epoch_end(self, trainer, module) -> None:
        # Runs on ALL ranks: metrics are mesh-global so every rank reaches
        # the same decision, and trainer.save_checkpoint is a collective
        # (gather on all ranks, write on rank 0) — rank-guarding here
        # would deadlock a multi-host mesh.
        epoch = trainer.current_epoch
        if (epoch + 1) % self.every_n_epochs != 0:
            return
        metrics = trainer.callback_metrics
        score = self._score(metrics)
        if self.monitor is not None and score is None:
            return  # monitored metric not produced this epoch
        os.makedirs(self.dirpath, exist_ok=True)
        name = self.filename.format(epoch=epoch, step=trainer.global_step)
        path = os.path.join(self.dirpath, name + ".ckpt")
        if self.async_write and hasattr(trainer, "flush_checkpoints"):
            trainer.save_checkpoint(path, async_write=True)
        else:
            # Sync, or a trainer facade without the async machinery.
            trainer.save_checkpoint(path)
            self._verify_written(trainer, path)
        if score is None:
            # monitor=None ⇒ Lightning semantics: "best" is simply the most
            # recent; rank saves by recency (global_step, mode=max) so
            # _prune keeps the latest k, not a stale early file.
            self.best_model_path = path
            self._saved.append((float(trainer.global_step), path))
            self._prune(trainer, force_mode="max")
            return
        if self._is_better(score):
            self.best_model_score = score
            self.best_model_path = path
        self._saved.append((score, path))
        self._prune(trainer)

    def _prune(self, trainer, force_mode: Optional[str] = None) -> None:
        if self.save_top_k < 0 or len(self._saved) <= self.save_top_k:
            return
        reverse = (force_mode or self.mode) == "max"
        ranked = sorted(self._saved, key=lambda t: t[0], reverse=reverse)
        keep = set(p for _, p in ranked[: self.save_top_k])
        keep.add(self.best_model_path)
        doomed = [p for _, p in self._saved if p not in keep]
        if self.async_write and hasattr(trainer, "flush_checkpoints"):
            # Never delete a path whose write may still be in flight —
            # but ONLY join when one actually is.  Joining every prune
            # made steady-state save_top_k=1 synchronous again: the
            # just-enqueued save is always the newest (kept) path, and
            # last epoch's doomed file finished writing long ago.  A
            # trainer without pending-write tracking gets the
            # conservative unconditional join.
            pending = getattr(trainer, "checkpoint_write_pending", None)
            if pending is None or any(pending(p) for p in doomed):
                trainer.flush_checkpoints()
        for score, path in list(self._saved):
            if path not in keep:
                # Bookkeeping runs on every rank (kept consistent for the
                # callback_states return), but file deletion is rank-0's —
                # co-located ranks share a filesystem and would race.
                if trainer.is_global_zero:
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
        self._saved = [(s, p) for s, p in self._saved if p in keep]

    def _verify_written(self, trainer, path: str) -> None:
        """Post-write integrity read-back (``verify=True``, rank 0)."""
        if not self.verify or not trainer.is_global_zero:
            return
        from ray_lightning_tpu.utils.sharded_ckpt import verify_checkpoint

        problems = verify_checkpoint(path)
        if problems:
            raise RuntimeError(
                f"checkpoint {path} failed post-write verification: "
                + "; ".join(str(p) for p in problems)
            )

    def on_fit_end(self, trainer, module) -> None:
        # Async writes were flushed by the loop just before this hook;
        # verify the surviving files now that their bytes are durable.
        if not (self.verify and self.async_write):
            return
        for _, path in self._saved:
            if os.path.exists(path):
                self._verify_written(trainer, path)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "best_model_path": self.best_model_path,
            "best_model_score": self.best_model_score,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best_model_path = state.get("best_model_path", "")
        self.best_model_score = state.get("best_model_score")


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving.

    ≙ Lightning ``EarlyStopping`` as exercised by reference
    ``test_ddp.py:289-308``.  Decision consistency across hosts: metrics
    are mesh-global (all-reduced inside the step functions), so every host
    reaches the same verdict on the same epoch — no extra broadcast needed.
    """

    def __init__(
        self,
        monitor: str = "val_loss",
        mode: str = "min",
        patience: int = 3,
        min_delta: float = 0.0,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best: Optional[float] = None
        self.wait = 0
        self.stopped_epoch: Optional[int] = None

    def _improved(self, value: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_validation_epoch_end(self, trainer, module) -> None:
        value = trainer.callback_metrics.get(self.monitor)
        if value is None:
            return
        value = float(value)
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                trainer.should_stop = True
                self.stopped_epoch = trainer.current_epoch

    def state_dict(self) -> Dict[str, Any]:
        return {"best": self.best, "wait": self.wait}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best = state.get("best")
        self.wait = state.get("wait", 0)


class CSVLogger(Callback):
    """Persist the training/validation curves to ``metrics.csv``.

    ≙ the Lightning loggers (CSV/TensorBoard) the reference inherits for
    free (``trainer.logged_metrics`` consumers, reference
    ``ray_ddp.py:377-385``): one row per epoch (and per val epoch) with
    the union of all metric keys seen so far.  Rank-0-only file writes;
    rows also round-trip worker→driver via ``state_dict`` so the
    driver-side callback object can be queried (``.rows`` / ``.path``)
    after a remote fit even without a shared filesystem.
    """

    def __init__(self, dirpath: Optional[str] = None,
                 filename: str = "metrics.csv"):
        self.dirpath = dirpath
        self.filename = filename
        self.rows: list = []
        self._flushed_rows = 0
        self._flushed_keys: list = []
        self._last_row_micro = 0

    @property
    def path(self) -> Optional[str]:
        if self.dirpath is None:
            return None
        return os.path.join(self.dirpath, self.filename)

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir, "csv")
        self._last_row_micro = 0

    def _append(self, trainer) -> None:
        row = {
            "epoch": trainer.current_epoch,
            "step": trainer.global_step,
            **{k: float(v) for k, v in trainer.callback_metrics.items()},
        }
        self.rows.append(row)
        if trainer.is_global_zero:
            self._flush()

    def _flush(self) -> None:
        import csv

        # Key sets can grow (val metrics appear after the first val
        # epoch).  Same keys ⇒ append only the new rows (per-step logging
        # must not rewrite an ever-growing file each batch); new keys ⇒
        # rewrite atomically so a reader never sees a torn file.
        keys: list = []
        for row in self.rows:
            for k in row:
                if k not in keys:
                    keys.append(k)
        os.makedirs(self.dirpath, exist_ok=True)
        if (keys == self._flushed_keys and self._flushed_rows
                and os.path.exists(self.path)):
            with open(self.path, "a", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=keys)
                writer.writerows(self.rows[self._flushed_rows:])
        else:
            tmp = self.path + ".tmp"
            with open(tmp, "w", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=keys)
                writer.writeheader()
                writer.writerows(self.rows)
            os.replace(tmp, self.path)
        self._flushed_rows = len(self.rows)
        self._flushed_keys = keys

    def on_train_epoch_start(self, trainer, module) -> None:
        # Anchor the row cadence at the epoch's ACTUAL starting
        # micro-step — checkpoint restore runs after setup(), so a fit
        # resumed at step 1003 must keep rows on the same
        # log_every_n_steps grid instead of emitting one spurious row
        # on its first post-resume hook (sync_point_crossed from 0 is
        # trivially true at any resume point).
        self._last_row_micro = getattr(trainer, "micro_step", 0) or 0

    def on_train_batch_end(self, trainer, module, logs, batch_idx) -> None:
        # Per-step rows on the trainer's log_every_n_steps cadence — a
        # 1-epoch LM run gets a real training curve, not a single row.
        # Cadence CROSSING (fault.drain.sync_point_crossed — the one
        # stride-aware boundary rule), not `% == 0`: under megastep
        # execution micro_step advances K per hook and can step over
        # exact multiples; one row per crossed boundary either way.
        # Metric values may lag one log interval (the loop's async log
        # fetch, docs/OBSERVABILITY.md) — the curve is intact, staged.
        n = getattr(
            getattr(trainer, "config", None), "log_every_n_steps", 0
        )
        micro = getattr(trainer, "micro_step", None)
        if n and micro and sync_point_crossed(
            self._last_row_micro, micro, n
        ):
            self._last_row_micro = micro
            self._append(trainer)

    def on_train_epoch_end(self, trainer, module) -> None:
        self._append(trainer)

    def on_validation_epoch_end(self, trainer, module) -> None:
        self._append(trainer)

    def state_dict(self) -> Dict[str, Any]:
        return {"rows": list(self.rows), "dirpath": self.dirpath}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.rows = list(state.get("rows", []))
        self.dirpath = state.get("dirpath", self.dirpath)


class ProfilerCallback(Callback):
    """Capture a ``jax.profiler`` trace of a training-step window.

    ≙ SURVEY §5: the reference has no profiler integration (only the
    ad-hoc ``CUDACallback`` timer); here the worker records an XLA/TPU
    trace — op-level timeline, HBM usage, fusion view — loadable in
    TensorBoard or Perfetto.  Rank 0 only by default (per-device timelines
    are near-identical under SPMD); pass ``rank_zero_only=False`` for one
    trace per worker.  Traces land in ``<dirpath>/rank<k>/`` (``dirpath``
    defaults to the telemetry output dir when the telemetry subsystem is
    active — so ``jax.profiler`` traces and the span exports land in one
    place — else ``<default_root_dir>/profiler``).  The window opens at
    the first step ``>= start_step`` — skipping early steps keeps
    compilation noise out of the capture; on a resumed run it opens
    immediately.

    ``schedule`` generalizes to several capture windows per fit:
    ``[(start_step, num_steps), ...]``.  Overlapping/touching windows
    are MERGED at construction — ``jax.profiler.start_trace`` raises on
    a second start, so overlap must never reach it — and the runtime
    start is additionally ``_active``-guarded (a resume that restores a
    stale ``_active=True``, or any double-fire, degrades to a skipped
    window, never a crash).  ``teardown`` is idempotent.
    """

    def __init__(self, dirpath: Optional[str] = None, start_step: int = 2,
                 num_steps: int = 3, rank_zero_only: bool = True,
                 schedule: Optional[list] = None):
        if schedule is None:
            if num_steps < 1:
                raise ValueError("num_steps must be >= 1")
            windows = [(int(start_step), int(num_steps))]
        else:
            if not schedule:
                raise ValueError("schedule must name at least one window")
            spans = []
            for item in schedule:
                s, n = int(item[0]), int(item[1])
                if s < 0 or n < 1:
                    raise ValueError(
                        f"schedule window {item!r}: start must be >= 0 "
                        "and num_steps >= 1"
                    )
                spans.append((s, s + n))
            # Merge overlapping/touching [start, end) intervals: two
            # windows covering the same step must become ONE start/stop
            # pair (double start_trace is a hard jax error).
            spans.sort()
            merged = [list(spans[0])]
            for s, e in spans[1:]:
                if s <= merged[-1][1]:
                    merged[-1][1] = max(merged[-1][1], e)
                else:
                    merged.append([s, e])
            windows = [(s, e - s) for s, e in merged]
        self.dirpath = dirpath
        self.start_step = windows[0][0]   # introspection compat
        self.num_steps = windows[0][1]
        self.rank_zero_only = rank_zero_only
        self._windows = windows
        self._win_i = 0
        self.trace_dir: Optional[str] = None
        self._active = False
        self._started_at: Optional[int] = None

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            tel_dir = getattr(trainer, "telemetry_dir", None)
            self.dirpath = (
                os.path.join(tel_dir, "profiler") if tel_dir
                else os.path.join(trainer.default_root_dir, "profiler")
            )
        # Fresh capture state per fit: callback objects are reused across
        # fits (tuner sweeps) and re-shipped to workers on elastic
        # restarts — stale ``_active``/window progress must never leak in.
        self._active = False
        self._win_i = 0
        self._started_at = None

    def _enabled(self, trainer) -> bool:
        return trainer.is_global_zero or not self.rank_zero_only

    def on_train_batch_end(self, trainer, module, logs, batch_idx) -> None:
        import jax

        if not self._enabled(trainer):
            return
        step = trainer.global_step
        if not self._active:
            if (self._win_i >= len(self._windows)
                    or step < self._windows[self._win_i][0]):
                return
            self.trace_dir = os.path.join(
                self.dirpath, f"rank{trainer.global_rank}"
            )
            os.makedirs(self.trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self.trace_dir)
            except RuntimeError as e:
                # A trace is already active (double-start from a stale
                # resume, or an outer jax.profiler.trace context): skip
                # this window rather than crash the fit.
                import warnings

                warnings.warn(f"ProfilerCallback: start_trace skipped ({e})")
                self._win_i += 1
                return
            self._active = True
            self._started_at = step
        elif step >= self._started_at + self._windows[self._win_i][1]:
            # Make the traced window's device work observable before stop.
            jax.block_until_ready(logs)
            try:
                jax.profiler.stop_trace()
            finally:
                self._active = False
                self._win_i += 1

    def teardown(self, trainer, module, stage: str) -> None:
        if not self._active:  # idempotent: second teardown is a no-op
            return
        import jax

        try:
            state = getattr(trainer, "state", None)
            if state is not None:  # flush async-dispatched traced work
                jax.block_until_ready(state)
            jax.profiler.stop_trace()
        finally:
            self._active = False

    def state_dict(self) -> Dict[str, Any]:
        return {"trace_dir": self.trace_dir}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.trace_dir = state.get("trace_dir")
        # A state dict can NEVER restore a live trace: a restored
        # ``_active=True`` would block every future window (or double-
        # stop a trace this process never started).
        self._active = False


class TelemetryCallback(Callback):
    """Span recording + artifact export for the telemetry subsystem.

    The loop records cheap-tier telemetry (counters, step-time split,
    headline ``callback_metrics``) on every fit without any callback.
    Adding this callback is the per-fit opt-in for the rest:

    * ``spans=True`` (default) upgrades the fit's tracer to record phase
      spans even when the global tier is ``cheap`` — the callback IS the
      explicit request, mirroring ``telemetry="full"`` on the strategy;
    * at teardown it exports span JSONL + Chrome trace + the snapshot
      into ``dirpath`` (default: the fit's telemetry dir — the same
      output-dir family ``ProfilerCallback`` folds its ``jax.profiler``
      traces into, so one directory opens the whole story in Perfetto);
    * ``.report`` on the driver-side callback object carries the rank-0
      snapshot after a remote fit (state-dict round-trip).
    """

    def __init__(self, dirpath: Optional[str] = None, spans: bool = True):
        self.dirpath = dirpath
        self.spans = spans
        self.report: Dict[str, Any] = {}
        self.export_paths: Dict[str, str] = {}

    def _tel(self, trainer):
        tel = getattr(trainer, "telemetry", None)
        return tel if tel is not None and tel.enabled else None

    def setup(self, trainer, module, stage: str) -> None:
        tel = self._tel(trainer)
        if self.dirpath is None:
            self.dirpath = (
                getattr(trainer, "telemetry_dir", None)
                or os.path.join(trainer.default_root_dir, "telemetry")
            )
        if tel is not None and self.spans:
            tel.tracer.enabled = True

    def on_fit_end(self, trainer, module) -> None:
        tel = self._tel(trainer)
        if tel is not None:
            self.report = tel.snapshot()

    def teardown(self, trainer, module, stage: str) -> None:
        tel = self._tel(trainer)
        if tel is None:
            return
        if not self.report:
            self.report = tel.snapshot()
        if tel.tracer.enabled:
            try:
                self.export_paths = tel.export(self.dirpath)
            except OSError as e:
                import warnings

                warnings.warn(f"telemetry export failed ({e})")

    def state_dict(self) -> Dict[str, Any]:
        return {
            "report": dict(self.report),
            "dirpath": self.dirpath,
            "export_paths": dict(self.export_paths),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.report = dict(state.get("report", {}))
        self.dirpath = state.get("dirpath", self.dirpath)
        self.export_paths = dict(state.get("export_paths", {}))


class DeviceStatsCallback(Callback):
    """Per-epoch wall time + device memory stats, mesh-averaged.

    TPU-native analogue of the reference's ``CUDACallback`` benchmark
    harness (``examples/ray_ddp_sharded_example.py:16-45``): epoch time and
    peak device memory, averaged across workers.  Uses
    ``jax.local_devices()[0].memory_stats()`` (populated on TPU; absent on
    the CPU test backend, where it degrades to wall-time only).
    """

    def __init__(self, log: bool = True):
        self.log = log
        self.epoch_times: list = []
        self.peak_memories: list = []
        self._t0 = 0.0

    def on_train_epoch_start(self, trainer, module) -> None:
        self._t0 = time.perf_counter()

    def on_train_epoch_end(self, trainer, module) -> None:
        dt = time.perf_counter() - self._t0
        self.epoch_times.append(dt)
        peak = None
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats:
                peak = stats.get("peak_bytes_in_use")
        except Exception:  # noqa: BLE001 - stats are best-effort
            peak = None
        if peak is not None:
            self.peak_memories.append(peak)
        trainer.log_metrics({"epoch_time_s": dt})
        if self.log and trainer.is_global_zero:
            mem = f", peak_mem={peak / 2**20:.0f}MiB" if peak else ""
            print(
                f"[rlt] epoch {trainer.current_epoch}: {dt:.2f}s{mem}",
                flush=True,
            )

    def summary(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if self.epoch_times:
            out["avg_epoch_time_s"] = float(np.mean(self.epoch_times))
        if self.peak_memories:
            out["avg_peak_memory_bytes"] = float(np.mean(self.peak_memories))
        return out

    # State round-trips worker→driver (loop.py "callback_states") so the
    # driver-side object can report summary() after a remote fit.
    def state_dict(self) -> Dict[str, Any]:
        return {
            "epoch_times": list(self.epoch_times),
            "peak_memories": list(self.peak_memories),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.epoch_times = list(state.get("epoch_times", []))
        self.peak_memories = list(state.get("peak_memories", []))


class StochasticWeightAveraging(Callback):
    """SWA: average the weights visited over the tail of training.

    ≙ ``pl.callbacks.StochasticWeightAveraging``.  From
    ``swa_start_epoch`` onward, the end-of-epoch params enter a running
    mean; at fit end the averaged weights REPLACE the trained ones, so
    the RETURNED state (``trainer.params``, the driver's recovered
    weights, a post-fit ``trainer.save_checkpoint``) is the SWA point.
    Checkpoints written DURING the fit (ModelCheckpoint epochs, elastic
    restart snapshots) predate the swap and hold the raw weights —
    serve from the post-fit state, not from a mid-fit
    ``best_model_path``.

    TPU-first: the running mean is a device pytree updated with one
    fused ``tree_map`` per epoch — no host round-trip, and sharded
    params average shard-local (the mean of identically-sharded trees
    is identically sharded, so no resharding or gather happens).

    Note the standard SWA caveat: the optimizer state is NOT averaged —
    resuming training from an SWA checkpoint restarts optimization at
    the averaged point.
    """

    def __init__(self, swa_start_epoch: int = 1):
        if swa_start_epoch < 0:
            raise ValueError("swa_start_epoch must be >= 0")
        self.swa_start_epoch = swa_start_epoch
        self._mean = None
        self._count = 0

    def on_fit_start(self, trainer, module) -> None:
        # Fresh average per fit: callback instances are reused across
        # fits (the tuner/A-B pattern), and folding a previous model's
        # weights into this fit's mean would corrupt it silently.
        self._mean = None
        self._count = 0

    def on_train_epoch_end(self, trainer, module) -> None:
        import jax
        import jax.numpy as jnp

        if trainer.current_epoch < self.swa_start_epoch:
            return
        params = trainer.state.params
        self._count += 1
        if self._mean is None:
            # COPY, never alias: the train step donates the state
            # buffers, so holding the live params pytree would leave the
            # mean pointing at deleted memory one step later.
            self._mean = jax.tree_util.tree_map(jnp.copy, params)
            return
        n = float(self._count)
        self._mean = jax.tree_util.tree_map(
            lambda m, p: m + (p.astype(m.dtype) - m) / n, self._mean, params
        )

    def on_fit_end(self, trainer, module) -> None:
        if self._mean is None:
            return
        from ray_lightning_tpu.core.module import TrainState

        st = trainer.state
        trainer.state = TrainState(
            self._mean, st.opt_state, st.step, st.grad_residual
        )

    # SWA state is NOT persisted across resumes: the running mean is a
    # full params-sized pytree — shipping it through every restart
    # checkpoint would double their size.  A resumed fit restarts the
    # average from the resume epoch (documented Lightning behavior for
    # mid-SWA restarts is similarly lossy).
    def state_dict(self) -> Dict[str, Any]:
        return {"swa_start_epoch": self.swa_start_epoch}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.swa_start_epoch = state.get(
            "swa_start_epoch", self.swa_start_epoch)


def _host_copy(tree, mesh=None):
    """Host numpy copy of a device pytree, safe on multi-host meshes —
    the shared replicate-then-get discipline (one cached jitted identity
    per mesh; also behind ``LoopContext._gathered_state``).  The
    replicate is a COLLECTIVE: on a multi-host mesh every rank must call
    this at the same point."""
    from ray_lightning_tpu.parallel.sharding import host_replicated_copy

    return host_replicated_copy(tree, mesh)


class ExponentialMovingAverage(Callback):
    """EMA of the weights: ``ema = d*ema + (1-d)*params`` per OPTIMIZER
    step — the standard eval/serving average for vision and diffusion
    workloads (SWA's uniform tail mean is the LM-style counterpart).

    TPU-first like SWA: the shadow is a device pytree updated with one
    fused ``tree_map`` (shard-local under GSPMD, no gathers).  Updates
    track ``trainer.global_step`` — under gradient accumulation the
    params change once per optimizer step, and so does the EMA (a
    micro-batch cadence would silently shrink the horizon by the
    accumulation factor).  ``update_every_n_steps`` thins the update
    cadence; the decay compounds over the steps actually elapsed, so
    the averaging horizon is cadence-independent.  Megastep execution
    (``megastep=K``) is the same contract from the other side: the
    hook fires once per stride with ``global_step`` advanced by up to
    K, the decay compounds ``decay**K`` against the stride-final
    params — horizon-preserving, tolerance-level different from
    per-step sampling (intermediate params are fused inside the scan
    and never materialize on host).

    At fit end the EMA weights REPLACE the trained ones in the returned
    state when ``swap_at_end=True`` (default).  With
    ``swap_at_end=False`` the shadow travels in the callback's
    ``state_dict`` (host-gathered), so it survives the worker→driver
    round-trip of remote strategies — read ``.ema_params`` on the
    driver-side callback after fit.  Mid-fit checkpoints predate any
    swap — same caveat as SWA.
    """

    def __init__(self, decay: float = 0.999,
                 update_every_n_steps: int = 1,
                 swap_at_end: bool = True):
        if not 0.0 < decay < 1.0:
            raise ValueError("decay must be in (0, 1)")
        if update_every_n_steps < 1:
            raise ValueError("update_every_n_steps must be >= 1")
        self.decay = decay
        self.update_every_n_steps = update_every_n_steps
        self.swap_at_end = swap_at_end
        self.ema_params = None
        self._last_step: Optional[int] = None

    def on_fit_start(self, trainer, module) -> None:
        # Fresh shadow per fit (callback instances are reused across
        # fits in tuner sweeps).
        self.ema_params = None
        self._last_step = None
        self._mesh = getattr(trainer, "mesh", None)
        self._host_ema = None

    def on_train_batch_end(self, trainer, module, logs, batch_idx) -> None:
        import jax
        import jax.numpy as jnp

        gs = trainer.global_step
        if gs == 0 or gs == self._last_step:
            return  # no optimizer update completed since the last EMA
        params = trainer.state.params
        if self.ema_params is None:
            # COPY, never alias — the train step donates state buffers.
            self.ema_params = jax.tree_util.tree_map(jnp.copy, params)
            self._last_step = gs
            return
        advanced = gs - self._last_step
        if advanced < self.update_every_n_steps:
            return
        # Compound over the optimizer steps actually elapsed.
        d = self.decay ** advanced
        self.ema_params = jax.tree_util.tree_map(
            lambda e, p: e * d + p.astype(e.dtype) * (1.0 - d),
            self.ema_params, params,
        )
        self._last_step = gs

    def on_accumulation_flush(self, trainer, module, logs, batch_idx):
        # The flush is one more optimizer step — fold it into the shadow
        # exactly like a window-completing micro-batch would have.
        self.on_train_batch_end(trainer, module, logs, batch_idx)

    def on_fit_end(self, trainer, module) -> None:
        if self.ema_params is None:
            return
        if not self.swap_at_end:
            # Pull the shadow host-side HERE — on_fit_end runs on every
            # rank, so the replicate collective inside _host_copy is
            # safe; state_dict (rank-0-only on remote strategies) then
            # serves the cached copy.  device_get alone would raise on a
            # multi-host ZeRO-3/TP mesh, where the shadow inherits the
            # params' sharding and is not fully addressable.
            self._host_ema = _host_copy(self.ema_params, self._mesh)
            return
        from ray_lightning_tpu.core.module import TrainState

        st = trainer.state
        trainer.state = TrainState(
            self.ema_params, st.opt_state, st.step, st.grad_residual
        )

    def state_dict(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {"decay": self.decay}
        if not self.swap_at_end and self.ema_params is not None:
            # The shadow is the run's whole point when not swapping;
            # ship it host-side so remote fits return it to the driver
            # (and resumes restore it).  Only in this mode — with
            # swap_at_end the returned state already carries it, and
            # doubling every checkpoint payload would be waste.
            if getattr(self, "_host_ema", None) is not None:
                state["ema_params"] = self._host_ema
            else:
                # Mid-fit call (restart-checkpoint metadata).  This call
                # site is rank-0-only, so a replicate COLLECTIVE here
                # would deadlock a multi-host mesh — gather only when
                # every shard is already addressable; otherwise omit the
                # shadow from this checkpoint (EMA restart is documented
                # lossy, like SWA) and let on_fit_end's all-ranks gather
                # ship it at fit end.
                import jax

                addressable = all(
                    getattr(x, "is_fully_addressable", True)
                    for x in jax.tree_util.tree_leaves(self.ema_params)
                )
                if addressable:
                    state["ema_params"] = _host_copy(
                        self.ema_params, getattr(self, "_mesh", None)
                    )
                else:
                    import warnings

                    warnings.warn(
                        "EMA shadow omitted from this mid-fit "
                        "checkpoint: it is not fully addressable and "
                        "state_dict ran on rank 0 only (a gather here "
                        "would deadlock the mesh); the fit-end "
                        "state_dict carries it."
                    )
        return state

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.decay = state.get("decay", self.decay)
        if "ema_params" in state:
            self.ema_params = state["ema_params"]
