from .module import TpuModule, TrainState
from .data import TpuDataModule, ArrayDataset, NumpyLoader, RandomDataset
from .callbacks import (
    Callback,
    ModelCheckpoint,
    EarlyStopping,
    CSVLogger,
    DeviceStatsCallback,
)
from .loop import FitConfig
from .trainer import Trainer

__all__ = [
    "TpuModule",
    "TrainState",
    "TpuDataModule",
    "ArrayDataset",
    "NumpyLoader",
    "RandomDataset",
    "Callback",
    "ModelCheckpoint",
    "EarlyStopping",
    "CSVLogger",
    "DeviceStatsCallback",
    "FitConfig",
    "Trainer",
]
