"""Data pipeline: datamodule protocol + numpy loaders with host sharding.

≙ the reference's reliance on torch ``DataLoader`` + ``DistributedSampler``
(sampler kwargs injected at reference ``ray_ddp.py:556-561``, asserted by
``test_ddp.py:179-211``).  TPU-idiomatic replacement: data never flows
through the control plane — each host loads/synthesizes its own **shard of
every global batch** (`shard_index = host_rank`, `num_shards = num_hosts`),
and the strategy turns per-host arrays into globally-sharded
``jax.Array``s via ``make_array_from_process_local_data``.

Loaders yield numpy (host) batches; device transfer is the strategy's job
so it can attach the right ``NamedSharding``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TpuDataModule",
    "ArrayDataset",
    "NumpyLoader",
    "RandomDataset",
]


class TpuDataModule:
    """≙ ``pl.LightningDataModule`` (used by reference examples/tests).

    Subclasses override the ``*_dataloader`` methods to return a
    :class:`NumpyLoader` (or any iterable of numpy-batch pytrees).  The
    strategy calls :meth:`set_shard` before ``setup`` so loaders can shard
    per host (the ``DistributedSampler`` analogue).
    """

    def __init__(self):
        self.shard_index: int = 0
        self.num_shards: int = 1

    def set_shard(self, shard_index: int, num_shards: int) -> None:
        self.shard_index = shard_index
        self.num_shards = num_shards

    def prepare_data(self) -> None:
        """Download/once-per-node work (≙ the init_hook FileLock pattern,
        reference ``examples/ray_ddp_tune.py:22-25``)."""

    def setup(self, stage: str) -> None:
        ...

    def train_dataloader(self):
        raise NotImplementedError

    def val_dataloader(self):
        return None

    def test_dataloader(self):
        return None

    def predict_dataloader(self):
        return None

    def teardown(self, stage: str) -> None:
        ...


class ArrayDataset:
    """A dataset over aligned numpy arrays (features, labels, ...)."""

    def __init__(self, **arrays: np.ndarray):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) > 1:
            raise ValueError(f"Array length mismatch: {sizes}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self.size = next(iter(sizes.values())) if sizes else 0

    def __len__(self) -> int:
        return self.size

    def take(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


class RandomDataset(ArrayDataset):
    """Synthetic regression data (≙ reference ``tests/utils.py:16-25``)."""

    def __init__(self, size: int = 32, length: int = 64, seed: int = 0):
        rng = np.random.default_rng(seed)
        super().__init__(x=rng.standard_normal((length, size), dtype=np.float32))


class NumpyLoader:
    """Batched iterator over an :class:`ArrayDataset` with host sharding.

    The global batch of size ``batch_size`` is split into ``num_shards``
    host shards; this loader yields THIS host's ``batch_size //
    num_shards`` examples per step, with a shuffle order derived from
    ``seed + epoch`` that is identical on every host (so shards never
    overlap — the ``DistributedSampler`` contract).

    ``drop_last=True`` semantics by default: a ragged final global batch is
    dropped, keeping shapes static for XLA (dynamic shapes would recompile
    every tail batch — SURVEY "XLA semantics").
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int = 32,
        shuffle: bool = False,
        seed: int = 0,
        shard_index: int = 0,
        num_shards: int = 1,
        drop_last: bool = True,
    ):
        if batch_size % num_shards != 0:
            raise ValueError(
                f"Global batch_size {batch_size} must divide evenly over "
                f"{num_shards} host shards."
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.drop_last = drop_last
        self.epoch = 0

    def set_shard(self, shard_index: int, num_shards: int) -> None:
        if self.batch_size % num_shards != 0:
            raise ValueError(
                f"Global batch_size {self.batch_size} must divide evenly "
                f"over {num_shards} host shards."
            )
        self.shard_index = shard_index
        self.num_shards = num_shards

    def set_epoch(self, epoch: int) -> None:
        """≙ ``DistributedSampler.set_epoch`` — reshuffle per epoch."""
        self.epoch = epoch

    @property
    def per_host_batch_size(self) -> int:
        return self.batch_size // self.num_shards

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        num_batches = len(self)
        for b in range(num_batches):
            start = b * self.batch_size
            global_idx = order[start : start + self.batch_size]
            # This host's contiguous slice of the global batch.
            per = len(global_idx) // self.num_shards
            lo = self.shard_index * per
            shard_idx = global_idx[lo : lo + per]
            yield self.dataset.take(shard_idx)
