"""TpuModule — the Lightning-shaped, JAX-native module protocol.

The reference keeps the user surface an unmodified ``LightningModule``
(``/root/reference/README.md:50-62``).  A torch module cannot execute under
XLA/pjit, so this framework defines a *LightningModule-shaped protocol*
written in JAX (SURVEY §7 "hard parts" #1, option (a)): same hook names and
division of responsibility — the module owns model math and optimizer
choice, the Trainer/strategy owns distribution — but every step method is a
**pure function of (params, batch, rng)** so the strategy can ``jax.jit``
/ ``shard_map`` it over a device mesh.

Key contracts:

* ``init_params(rng)`` must be deterministic in ``rng`` — workers
  initialize locally from a broadcast seed instead of receiving traced
  objects over the wire (≙ ``PL_GLOBAL_SEED`` broadcast, reference
  ``ray_ddp.py:223``).
* ``training_step`` returns ``(loss, logs)``; the strategy differentiates
  it, so it must be traceable (no Python side effects on the hot path; use
  ``logs`` for metrics).
* The module object itself must be cloudpickle-able: it is shipped
  driver → workers through the object store (≙ ``ray.put(model)``,
  reference ``ray_ddp.py:339-342``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["TpuModule", "TrainState"]

Logs = Dict[str, jax.Array]


@jax.tree_util.register_pytree_node_class
class TrainState:
    """Minimal training state pytree: params + optimizer state + step.

    Unlike flax's ``TrainState`` it carries **no static function fields**
    (``apply_fn``/``tx``) — the optimizer transformation lives in the
    strategy, so the whole state is a pure array pytree that can be
    sharded, donated, state-streamed and checkpointed without special
    casing (the property behind topology-independent checkpoints,
    SURVEY §7 hard-part #4).

    ``grad_residual`` (default ``None`` — an *empty* pytree node, so
    legacy 3-field states flatten/unflatten and checkpoint identically)
    carries the per-device error-feedback residual of quantized gradient
    sync (``parallel/grad_sync.py``, ``grad_comm="int8_ef"``): one f32
    row per sync participant, sharded so row ``d`` lives on device ``d``.
    """

    def __init__(
        self,
        params: Any,
        opt_state: Any,
        step: jax.Array,
        grad_residual: Any = None,
    ):
        self.params = params
        self.opt_state = opt_state
        self.step = step
        self.grad_residual = grad_residual

    def tree_flatten(self):
        return (
            self.params, self.opt_state, self.step, self.grad_residual
        ), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, params: Any, tx) -> "TrainState":
        return cls(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def apply_gradients(self, grads: Any, tx) -> "TrainState":
        updates, new_opt_state = tx.update(grads, self.opt_state, self.params)
        import optax

        new_params = optax.apply_updates(self.params, updates)
        return TrainState(
            new_params, new_opt_state, self.step + 1, self.grad_residual
        )

    def __repr__(self):
        n = sum(
            x.size for x in jax.tree_util.tree_leaves(self.params)
            if hasattr(x, "size")
        )
        return f"TrainState(step={self.step}, params={n} elems)"


class TpuModule:
    """Base class for user models (≙ ``pl.LightningModule``).

    Subclasses implement::

        class MyModel(TpuModule):
            def __init__(self, hidden=128):
                super().__init__()
                self.save_hyperparameters(hidden=hidden)

            def init_params(self, rng):
                ...  # build the initial param pytree (e.g. flax init)

            def training_step(self, params, batch, rng):
                loss = ...
                return loss, {"train_loss": loss}

            def validation_step(self, params, batch):
                return {"val_loss": ...}

            def configure_optimizers(self):
                return optax.adam(1e-3)
    """

    def __init__(self):
        self.hparams: Dict[str, Any] = {}
        self.trainer = None  # set by the loop (worker-side context)
        self.precision: str = "f32"
        # Warm-start hook: set to a host param pytree (matching
        # ``init_params``'s structure) to start ``fit`` from those
        # weights instead of a fresh init — e.g. weights imported from a
        # torch/HF checkpoint (``utils/hf_import.py``).  Sharded onto
        # the active mesh exactly like fresh params.
        self.initial_params = None

    # -- configuration ------------------------------------------------------
    def save_hyperparameters(self, **kwargs: Any) -> None:
        self.hparams.update(kwargs)

    def configure_optimizers(self):
        """Return an ``optax.GradientTransformation``.

        ≙ ``LightningModule.configure_optimizers``; may also return a tuple
        ``(tx, lr_schedule_fn)`` where the schedule is used for logging.
        """
        raise NotImplementedError

    # -- model math (pure) --------------------------------------------------
    def init_params(self, rng: jax.Array) -> Any:
        """Deterministically build the initial parameter pytree."""
        raise NotImplementedError

    def training_step(
        self, params: Any, batch: Any, rng: jax.Array
    ) -> Tuple[jax.Array, Logs]:
        """One forward+loss on one (per-device or global) batch shard.

        Must be jax-traceable; the strategy wraps it in ``value_and_grad``
        and inserts/relies-on the data-parallel mean (the analogue of DDP's
        bucketed all-reduce, reference ``ray_ddp.py:483``).
        """
        raise NotImplementedError

    def validation_step(self, params: Any, batch: Any) -> Logs:
        raise NotImplementedError

    def test_step(self, params: Any, batch: Any) -> Logs:
        return self.validation_step(params, batch)

    def predict_step(self, params: Any, batch: Any) -> Any:
        raise NotImplementedError

    # -- lifecycle hooks (run on workers, inside the fit loop) --------------
    def setup(self, stage: str) -> None:
        """Called on each worker before the loop ('fit'|'validate'|'test'|'predict')."""

    def on_fit_start(self) -> None:
        ...

    def on_fit_end(self) -> None:
        ...

    def on_train_epoch_start(self, epoch: int) -> None:
        ...

    def on_train_epoch_end(self, epoch: int, metrics: Dict[str, float]) -> None:
        ...

    def on_validation_epoch_end(self, metrics: Dict[str, float]) -> None:
        ...

    def teardown(self, stage: str) -> None:
        ...
