"""The fit/eval/predict loops — run identically inline or on worker actors.

≙ the body of ``trainer.run_stage()`` that the reference executes remotely
on every actor (reference ``ray_ddp.py:487``): epochs × batches of a jitted
train step, callbacks firing between batches/epochs, validation interleaved,
rank-0 returning (state stream, metrics, best path) to the driver
(``ray_ddp.py:490-519``).

The :class:`LoopContext` is the worker-side stand-in for the Trainer that
callbacks and modules see (``trainer`` argument) — a deliberate duck-typed
subset so the same callback code runs on driver-inline and remote paths.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.core.callbacks import Callback, ModelCheckpoint
from ray_lightning_tpu.core.data import TpuDataModule
from ray_lightning_tpu.core.module import TpuModule, TrainState
from ray_lightning_tpu.fault import drain as drain_mod
from ray_lightning_tpu.fault import inject as chaos
from ray_lightning_tpu.fault.drain import PreemptedError
from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel import step_fns
from ray_lightning_tpu.parallel.overlap import (
    normalize_grad_overlap,
    resolve_grad_overlap,
)
from ray_lightning_tpu.telemetry import Telemetry
from ray_lightning_tpu.telemetry import program_ledger
from ray_lightning_tpu.utils.state_stream import (
    load_state_stream,
    state_stream_from_file,
    state_stream_to_file,
    to_state_stream,
)

__all__ = ["FitConfig", "LoopContext", "run_fit", "run_eval", "run_predict"]


@dataclasses.dataclass
class FitConfig:
    """Picklable trainer configuration shipped to workers.

    ≙ the Trainer args the reference pickles wholesale inside the trainer
    object (``ray_ddp.py:339-342``); we ship only the loop-relevant subset.
    """

    max_epochs: int = 1
    max_steps: int = -1
    check_val_every_n_epoch: int = 1
    limit_train_batches: int = -1
    limit_val_batches: int = -1
    log_every_n_steps: int = 50
    # Apply the optimizer once every k micro-batches (optax.MultiSteps
    # under the hood): k micro-steps of batch B train like one step of
    # batch k*B (≙ Lightning's ``accumulate_grad_batches``).  As in
    # Lightning, ``max_steps`` AND ``global_step`` count OPTIMIZER steps;
    # ``log_every_n_steps`` fires on micro-batches (Lightning's batch
    # cadence).  A partial accumulation window left at epoch end is
    # FLUSHED (one optimizer step from the averaged micro-grads), again
    # matching Lightning.
    accumulate_grad_batches: int = 1
    # Megastep execution (the host-dispatch optimization): fuse K
    # micro-steps into ONE jitted lax.scan per stride, with batches
    # pre-staged K at a time and metric accumulation on device — Python
    # re-enters once per stride instead of once per micro-batch
    # (docs/PERFORMANCE.md "Host dispatch & megastep").  Values:
    # None (read the RLT_MEGASTEP env bus, default "auto"), "auto"
    # (K=8 on TPU backends where per-step dispatch is the ceiling; off
    # on CPU), "off"/1, or an explicit int K >= 1.  Partial strides at
    # epoch/limit/max_steps boundaries fall back to the per-step path,
    # so step-count contracts hold exactly.
    megastep: Optional[Any] = None
    # Cross-replica sharded weight update (arXiv:2004.13336): on a
    # pure-DP mesh with a replicated optimizer (zero_stage=0), annotate
    # the optimizer state — and therefore the update computation —
    # sharded over the batch axes, so each replica updates 1/P of the
    # moments (reduce-scatter → sharded update → all-gather params,
    # inserted by GSPMD from the in/out shardings).  Values: None (read
    # the RLT_UPDATE_SHARDING env bus, default "auto"), "auto" (on for
    # TPU batch-only gspmd meshes, off on CPU), "on", "off"/bools.
    # Gated off wherever ZeRO already shards the state.
    update_sharding: Optional[Any] = None
    # Backward-overlapped gradient sync (parallel/overlap.py): split the
    # model trunk into G sub-scans and run each param group's bucketed
    # quantized all-reduce inside the backward via custom_vjp grad taps,
    # so the collectives hide under remaining backward compute instead
    # of firing serialized after jax.grad.  Values: None (read the
    # RLT_GRAD_OVERLAP env bus, forwarded to workers like
    # RLT_GRAD_COMM), "off"/""/0 (step-end sync, the zero-risk
    # default), or an int G >= 1.  Composes with grad_comm (the wire
    # codec is unchanged — only WHERE the collectives fire moves); with
    # grad_comm=full only the bitwise-neutral trunk segmentation runs.
    grad_overlap_segments: Optional[Any] = None
    seed: int = 0
    precision: str = "f32"
    default_root_dir: str = "."
    resume_from_checkpoint: Optional[str] = None
    fast_dev_run: bool = False
    # Elastic-restart support (strategy-managed): when set, every
    # ``restart_every_n_epochs`` the loop writes a topology-independent
    # checkpoint here so the strategy can respawn dead workers and resume.
    restart_dir: Optional[str] = None
    # None = unset: the strategy's elastic default applies.  An explicit
    # Trainer(restart_every_n_epochs=...) always wins over the strategy.
    restart_every_n_epochs: Optional[int] = None

    def __post_init__(self):
        # Lightning habits: None means "no limit/cap" for these — accept
        # it as a synonym for the framework's -1 sentinel instead of
        # crashing at a `>= 0` comparison deep in the loop.  A None
        # max_epochs additionally requires a real max_steps (otherwise
        # the fit would never terminate); Lightning's default in that
        # case is 1000 epochs, mirrored here as the range bound.
        if self.limit_train_batches is None:
            self.limit_train_batches = -1
        if self.limit_val_batches is None:
            self.limit_val_batches = -1
        if self.max_steps is None:
            self.max_steps = -1
        if self.max_epochs is None:
            self.max_epochs = 1000
        # Precision aliases: Lightning 2.x spellings map onto the two
        # real TPU modes (f32 / bf16 with f32 accumulation).  Anything
        # else — notably fp16, which TPUs don't accelerate — is rejected
        # loudly rather than silently training in f32.
        # Lossy aliases change semantics, not just spelling: Lightning's
        # '-true' means the WEIGHTS are cast to bf16, but this framework
        # only implements mixed bf16 (f32 params + optimizer state, bf16
        # compute) — coerce, but say so, since memory footprint and
        # numerics differ from what was asked for.
        lossy = {"bf16-true": "bf16"}
        aliases = {"32": "f32", "32-true": "f32", "float32": "f32",
                   "bf16-mixed": "bf16", "bfloat16": "bf16", **lossy}
        raw = str(self.precision)
        if raw in lossy:
            import warnings

            warnings.warn(
                f"precision={raw!r} (bf16 weights) is not implemented on "
                f"this framework; using mixed bf16 instead (f32 "
                f"params/optimizer state, bf16 matmuls). Pass "
                f"'bf16-mixed' to silence this warning."
            )
        self.precision = aliases.get(raw, self.precision)
        if self.precision not in ("f32", "bf16"):
            raise ValueError(
                f"precision {self.precision!r} unsupported on TPU: use "
                f"'f32' or 'bf16' (accepted aliases: {sorted(aliases)})"
            )
        # Megastep knob: validated eagerly (a typo'd value must fail at
        # Trainer construction, not minutes later on a worker); the
        # BACKEND-dependent "auto" resolution stays fit-time
        # (_resolve_megastep) — the driver may be CPU-only while the
        # workers run TPUs.
        _normalize_megastep(self.megastep)
        _normalize_update_sharding(self.update_sharding)
        normalize_grad_overlap(self.grad_overlap_segments)
        if self.fast_dev_run:
            self.max_epochs = 1
            self.limit_train_batches = 1
            self.limit_val_batches = 1


def _normalize_megastep(value: Any) -> Optional[Any]:
    """Validate a megastep knob value and return its normal form:
    None, "auto", "off" or an int >= 1 (numeric strings become ints;
    resolution to a concrete K happens at fit time)."""
    if value is None:
        return None
    if isinstance(value, str):
        s = value.strip().lower()
        if s in ("auto", "off", ""):
            return "off" if s == "" else s
        try:
            value = int(s)
        except ValueError:
            raise ValueError(
                f"megastep={value!r}: expected 'auto', 'off' or an "
                "integer K >= 1"
            ) from None
    if isinstance(value, bool) or not isinstance(value, int):
        raise TypeError(
            f"megastep must be None, 'auto', 'off' or an int >= 1; got "
            f"{type(value).__name__}"
        )
    if value < 1:
        raise ValueError(f"megastep must be >= 1, got {value}")
    return value


def _normalize_update_sharding(value: Any) -> Optional[str]:
    """Validate an ``update_sharding`` knob value: None, "auto", "on"
    or "off" (bools accepted as on/off).  Resolution against the real
    mesh/mode happens at fit time (:func:`_resolve_update_sharding`)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "on" if value else "off"
    if isinstance(value, str):
        s = value.strip().lower()
        if s == "":
            return "off"
        if s in ("auto", "on", "off"):
            return s
    raise ValueError(
        f"update_sharding={value!r}: expected 'auto', 'on', 'off' or a "
        "bool"
    )


def _resolve_update_sharding(
    config: FitConfig, mesh, mode: str, zero_stage: int
) -> bool:
    """Whether THIS fit shards the weight update over the batch axes
    (arXiv:2004.13336 via sharding annotations — see
    :func:`init_train_state`).

    Strongest first: the Trainer/strategy knob → the
    ``RLT_UPDATE_SHARDING`` env bus → ``"auto"``.  The technique only
    exists for replicated-optimizer data-parallel meshes, so it
    requires: a multi-device mesh whose axes are all batch-parallel
    (``data``/``fsdp``), gspmd step mode, and ``zero_stage == 0`` —
    ZeRO already shards the update, shard_map replicates the state by
    contract, and model-parallel axes change what "replica" means.  An
    explicit "on" outside that envelope warns and stays off (the same
    loud-downgrade discipline as grad_comm); "auto" additionally keeps
    CPU meshes off — like megastep, the XLA:CPU collective rendezvous
    costs more than the update traffic it saves, so auto engages on
    TPU backends only.
    """
    value = _normalize_update_sharding(config.update_sharding)
    if value is None:
        value = _normalize_update_sharding(
            os.environ.get("RLT_UPDATE_SHARDING", "auto")
        )
    if value == "off":
        return False
    eligible = (
        mesh is not None
        and getattr(mesh, "size", 1) > 1
        and mode == "gspmd"
        and zero_stage == 0
        and set(mesh.axis_names) <= {"data", "fsdp"}
    )
    if value == "on":
        if not eligible:
            import warnings

            warnings.warn(
                "update_sharding='on' needs a multi-device batch-only "
                "(data/fsdp) gspmd mesh with zero_stage=0 (ZeRO already "
                f"shards the update); got mesh="
                f"{None if mesh is None else tuple(mesh.axis_names)}, "
                f"mode={mode!r}, zero_stage={zero_stage} — running with "
                "a replicated update instead"
            )
            return False
        return True
    # auto
    if not eligible:
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def _resolve_megastep(config: FitConfig) -> int:
    """The concrete stride length K for this fit.

    Strongest first: an explicit ``megastep=`` on the Trainer/strategy →
    the ``RLT_MEGASTEP`` env bus (forwarded to workers like
    ``RLT_GRAD_COMM``) → ``"auto"``.  Auto picks K=8 on TPU backends —
    there the ~ms-scale per-step host dispatch is the throughput ceiling
    the MFU telemetry sees (ISSUE 5 / Podracer) — and stays off on
    CPU/other backends, where execution is effectively synchronous and
    fusing strides buys little while coarsening hook/drain granularity.
    """
    value = config.megastep
    if value is None:
        # NB: an empty RLT_MEGASTEP= means "off" (the operator cleared
        # the knob), same as every other normalization path — only a
        # genuinely unset var falls through to auto.
        value = os.environ.get("RLT_MEGASTEP")
        value = "auto" if value is None else value
    value = _normalize_megastep(value)
    if value == "off":
        return 1
    if value == "auto":
        try:
            on_tpu = jax.default_backend() == "tpu"
        except RuntimeError:
            on_tpu = False
        return 8 if on_tpu else 1
    return int(value)


class LoopContext:
    """Worker-side trainer context (the ``trainer`` arg of every hook)."""

    def __init__(
        self,
        config: FitConfig,
        global_rank: int,
        world_size: int,
        mesh=None,
        queue=None,
        tx=None,
    ):
        self.config = config
        self.global_rank = global_rank
        self.world_size = world_size
        self.mesh = mesh
        self.queue = queue
        self.tx = tx
        self._ckpt_queue = None  # lazy async checkpoint writer
        self.current_epoch = 0
        # Lightning convention: global_step counts OPTIMIZER steps;
        # micro_step counts micro-batches (they differ only under
        # gradient accumulation).
        self.global_step = 0
        self.micro_step = 0
        # Live-monitor progress signal (telemetry/heartbeat.py): a
        # counter that advances on ANY forward motion — train
        # micro-batches AND validation batches — plus a coarse phase
        # tag.  The heartbeat publisher reads both from its own thread;
        # the RunMonitor flags a rank whose progress freezes while its
        # beats keep flowing (the wedged-collective signature).
        self.progress = 0
        self.phase = "init"
        self.should_stop = False
        self.callback_metrics: Dict[str, float] = {}
        self.logged_metrics: Dict[str, float] = {}
        # Crash-forensics hook (telemetry/flight_recorder.py): lands any
        # in-flight _AsyncLogFetch boundary into callback_metrics before
        # the bundle snapshots them — without it a crash would freeze
        # the metrics one-to-two log intervals behind where the old
        # synchronous device_get path left them.
        self.pending_log_flush: Optional[Callable[[], None]] = None
        self.state: Optional[TrainState] = None
        self.default_root_dir = config.default_root_dir
        # Gradient-communication status (populated by run_fit): modules
        # consult ``grad_sync_active`` to pick per-device-safe compute
        # paths when their step runs inside the quantized-sync island.
        self.grad_sync_active = False
        self.comm_stats: Dict[str, Any] = {}
        # Backward-overlapped sync (populated by run_fit): the resolved
        # trunk-segment count G (0 = step-end).  Module forwards read it
        # to segment their layer scan; during the overlapped island's
        # differentiation ``grad_tap_plane`` additionally carries the
        # per-trace tap registry (parallel/overlap.py TapPlane).
        self.grad_overlap_segments = 0
        self.grad_tap_plane = None
        # Telemetry runtime for this stage (always present; tier "off"
        # degrades every surface to a no-op).  ``telemetry_dir`` is where
        # exporters (span dumps, ProfilerCallback traces) co-locate.
        self.telemetry: Optional[Telemetry] = None
        self.telemetry_dir: Optional[str] = None

    @property
    def is_global_zero(self) -> bool:
        return self.global_rank == 0

    def log_metrics(self, metrics: Dict[str, Any]) -> None:
        for k, v in metrics.items():
            self.logged_metrics[k] = float(v)
            self.callback_metrics[k] = float(v)

    # -- checkpointing ------------------------------------------------------
    def _gathered_state(self) -> Any:
        """Host-local numpy copy of the full train state.

        Single host: every shard is addressable, ``device_get`` suffices.
        Multi-host: replicate via an identity jit with replicated
        out_shardings (an XLA all-gather over ICI/DCN), then device_get the
        local replica — checkpoints stay topology-independent (SURVEY §7
        hard-part #4).

        **COLLECTIVE**: on a multi-host mesh every rank MUST call this at
        the same point (rank-guarding the caller deadlocks the mesh — only
        the file WRITE may be rank-guarded).
        """
        state = self.state
        if getattr(state, "grad_residual", None) is not None:
            # The EF residual is (n_devices, ~param_count) f32 — one
            # params-sized row PER DEVICE.  Gathering it would blow up
            # every checkpoint payload and the rank-0→driver stream by
            # n_devices × model size (device OOM at pod scale), to
            # preserve at most one step of compression error; resumes
            # re-attach a zero residual instead
            # (``GradSync.reconcile_resumed_state``).  The sharded
            # restart path (``sharded_ckpt.save_shard``) still persists
            # it cheaply — each host writes only its own rows.
            state = TrainState(state.params, state.opt_state, state.step)
        tel = self.telemetry
        if tel is None:
            return shardlib.host_replicated_copy(state, self.mesh)
        with tel.span("host_transfer"):
            out = shardlib.host_replicated_copy(state, self.mesh)
        tel.add_counter("host_transfers", 1)
        return out

    def checkpoint_payload(self, extra: Optional[Dict[str, Any]] = None) -> dict:
        return {
            "state": self._gathered_state(),
            "epoch": self.current_epoch,
            "global_step": self.global_step,
            "micro_step": self.micro_step,
            "callback_metrics": dict(self.callback_metrics),
            **(extra or {}),
        }

    def save_checkpoint(self, path: str, async_write: bool = False) -> None:
        """Gather (all ranks — collective) and write (rank 0 only).

        ``async_write=True`` moves serialization + disk IO to a single
        background writer thread, so the training loop resumes as soon
        as the host gather finishes — at GPT scale the msgpack encode +
        write is seconds per checkpoint that otherwise stall every
        epoch.  The GATHER stays synchronous on all ranks (it is a
        collective; backgrounding it would deadlock the mesh).  Pending
        writes are joined by :meth:`flush_checkpoints` (called at fit
        end, and by consumers before they read/delete checkpoint
        files); a failed background write raises there.
        """
        payload = self.checkpoint_payload()
        if not self.is_global_zero:
            return
        if self.telemetry is not None:
            self.telemetry.add_counter("checkpoint_writes", 1)
        tracer = (
            self.telemetry.tracer if self.telemetry is not None else None
        )
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not async_write:
            if tracer is None:
                state_stream_to_file(to_state_stream(payload), path)
                return
            with tracer.span("checkpoint_write", path=path):
                state_stream_to_file(to_state_stream(payload), path)
            return
        if self._ckpt_queue is None:
            import queue as _q

            # maxsize=1: at most ONE payload (a full host copy of the
            # train state — GBs at LM scale) waits in RAM; a slow disk
            # backpressures the loop instead of accumulating copies.
            self._ckpt_queue = _q.Queue(maxsize=1)
            self._ckpt_errors: List[BaseException] = []
            # Paths with an enqueued-but-unfinished write: consumers that
            # only need to delete a FINISHED file (ModelCheckpoint._prune)
            # consult this instead of joining the whole queue — joining
            # unconditionally turned steady-state save_top_k=1 back into
            # a synchronous write every epoch.
            self._ckpt_pending: set = set()
            self._ckpt_lock = threading.Lock()
            q, errors = self._ckpt_queue, self._ckpt_errors
            pending, lock = self._ckpt_pending, self._ckpt_lock
            wtracer = tracer  # tracer holds no device state — safe capture

            def writer():  # captures the queue/list, NOT self — the
                # LoopContext (with its device-side state) must stay
                # collectable once the writer is closed.
                while True:
                    item = q.get()
                    try:
                        if item is None:
                            return
                        p, pl = item
                        t0 = time.perf_counter()
                        state_stream_to_file(to_state_stream(pl), p)
                        if wtracer is not None:
                            wtracer.record(
                                "checkpoint_write", t0,
                                time.perf_counter() - t0,
                                args={"path": p, "async": True},
                            )
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                    finally:
                        if item is not None:
                            with lock:
                                pending.discard(item[0])
                        q.task_done()

            self._ckpt_thread = threading.Thread(
                target=writer, name="rlt-ckpt-writer", daemon=True
            )
            self._ckpt_thread.start()
        with self._ckpt_lock:
            self._ckpt_pending.add(path)
        self._ckpt_queue.put((path, payload))

    def checkpoint_write_pending(self, path: str) -> bool:
        """True while an async write of ``path`` is still enqueued or in
        flight.  False for finished writes, sync writes, and trainer
        facades without the async machinery — so callers can gate a
        flush on it unconditionally."""
        if getattr(self, "_ckpt_queue", None) is None:
            return False
        with self._ckpt_lock:
            return path in self._ckpt_pending

    def flush_checkpoints(self) -> None:
        """Join pending async checkpoint writes; re-raise any failure.
        A checkpoint the user believes exists must exist — a silently
        dropped write is worse than a loud one."""
        if getattr(self, "_ckpt_queue", None) is None:
            return
        self._ckpt_queue.join()
        if self._ckpt_errors:
            err = self._ckpt_errors[:]
            self._ckpt_errors.clear()
            raise RuntimeError(
                f"async checkpoint write failed: {err[0]!r}"
            ) from err[0]

    def close_checkpoint_writer(self) -> None:
        """Flush, then retire the writer thread (one per fit, never one
        per process lifetime — tuner sweeps run many fits)."""
        if getattr(self, "_ckpt_queue", None) is None:
            return
        try:
            self.flush_checkpoints()
        finally:
            self._ckpt_queue.put(None)
            self._ckpt_thread.join(timeout=30)
            self._ckpt_queue = None
            self._ckpt_thread = None


def _call_hooks(callbacks: List[Callback], hook: str, *args) -> None:
    for cb in callbacks:
        getattr(cb, hook)(*args)


def _maybe_export_telemetry(tel: Telemetry, out_dir: Optional[str]) -> None:
    """Full tier: drop this rank's span dump + Chrome trace + snapshot
    beside any ProfilerCallback capture (same output dir family).  A
    failed export warns — telemetry must never cost the stage result."""
    if not (tel.tracer.enabled and out_dir):
        return
    try:
        tel.export(out_dir)
    except OSError as e:
        import warnings

        warnings.warn(f"telemetry export failed ({e})")


def _mesh_barrier(mesh) -> None:
    """Block until every process of the mesh reaches this point: a tiny
    all-reduce over a mesh-sharded vector (completion of the local result
    requires every participant's contribution)."""
    if mesh is None or len(mesh.devices.flat) <= 1:
        return
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(mesh.devices.flat)
    vec = jnp.ones((n,), jnp.int32)
    sharded = NamedSharding(mesh, P(mesh.axis_names))
    total = jax.jit(
        jnp.sum, in_shardings=(sharded,), out_shardings=NamedSharding(
            mesh, P())
    )(jax.device_put(vec, sharded))
    assert int(jax.device_get(total)) == n


def _make_drain_poll(mesh, world_size: int):
    """Mesh-coordinated drain agreement (the Orbax-style preemption
    sync point): every process contributes its local drain flag to a
    tiny all-reduce, so ALL ranks decide to drain at the SAME step —
    a rank draining alone would tear the sharded drain checkpoint and
    deadlock its peers' next collective.

    Single-process fits return ``None`` (the local flag IS the global
    flag — zero overhead on the bench path).  The jitted reduction is
    built once and reused every step; per-step cost is one scalar-ish
    collective dispatch.
    """
    if mesh is None or world_size <= 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(mesh.devices.flat)
    sharded = NamedSharding(mesh, P(mesh.axis_names))
    total = jax.jit(
        jnp.sum, in_shardings=(sharded,),
        out_shardings=NamedSharding(mesh, P()),
    )

    def _shard_block(index) -> np.ndarray:
        s = index[0]
        start = 0 if s.start is None else s.start
        stop = n if s.stop is None else s.stop
        return _flag_box[0][: stop - start]

    _flag_box = [np.zeros((n,), np.int32)]

    def poll(local: bool) -> bool:
        _flag_box[0] = np.full((n,), 1 if local else 0, np.int32)
        arr = jax.make_array_from_callback((n,), sharded, _shard_block)
        return int(jax.device_get(total(arr))) > 0

    return poll


def _prune_restart_dir(restart_dir: str, keep: int = 2) -> None:
    """Keep the ``keep`` newest COMPLETE restart/drain checkpoints.

    Two, not one: previous-good fallback (restart discovery walks back
    over a corrupt newest checkpoint) is only possible if the previous
    checkpoint still exists — keeping exactly the newest would convert
    one bit flip into a from-scratch restart.  Candidate enumeration
    and ordering are SHARED with restart discovery
    (``sharded_ckpt.list_restart_candidates``) so pruning can never
    delete what discovery would have resumed from.
    """
    from ray_lightning_tpu.utils.sharded_ckpt import (
        list_restart_candidates,
    )

    import shutil

    for _, _, _, stale in list_restart_candidates(restart_dir)[keep:]:
        shutil.rmtree(stale, ignore_errors=True)
        if os.path.isfile(stale):  # legacy single-file
            try:
                os.unlink(stale)
            except OSError:
                pass


def _build_accum_flush(inner_tx, mesh, state_shardings):
    """Compile the partial-accumulation flush: one optimizer update from
    ``MultiStepsState.acc_grads`` (the running MEAN of the window's
    micro-grads), with the window counters reset.

    Without this, micro-batches left in an unfinished window at epoch/fit
    end were silently dropped (their gradients never reached the params)
    — diverging from Lightning, where the last incomplete window of an
    epoch still steps.
    """
    import optax

    def flush(state: TrainState) -> TrainState:
        ms = state.opt_state
        updates, inner2 = inner_tx.update(
            ms.acc_grads, ms.inner_opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_ms = optax.MultiStepsState(
            mini_step=jnp.zeros_like(ms.mini_step),
            gradient_step=ms.gradient_step + 1,
            inner_opt_state=inner2,
            acc_grads=jax.tree_util.tree_map(
                jnp.zeros_like, ms.acc_grads
            ),
        )
        return TrainState(
            new_params, new_ms, state.step + 1, state.grad_residual
        )

    if mesh is None or state_shardings is None:
        return jax.jit(flush, donate_argnums=0)
    return jax.jit(
        flush,
        in_shardings=(state_shardings,),
        out_shardings=state_shardings,
        donate_argnums=0,
    )


def _rederive_accum(old_world: int, old_accum: int,
                    new_world: int) -> Optional[int]:
    """The accumulation factor that keeps the GLOBAL batch per optimizer
    step invariant under an elastic world-size change: each host feeds
    ``b`` rows per micro-batch, so ``world × accum × b`` rows reach every
    optimizer update — resuming N→M must scale accum by N/M.  The LR
    schedule indexes optimizer steps, so with the global batch invariant
    it needs no rescaling.  Returns ``None`` when the product does not
    divide (the caller keeps the old accum and warns loudly)."""
    rows = int(old_world) * int(old_accum)
    if new_world <= 0 or rows % int(new_world):
        return None
    return rows // int(new_world)


def _elastic_resume_info(path: str, world_size: int,
                         cfg_accum: int) -> Optional[Dict[str, Any]]:
    """World-size delta between a sharded checkpoint and THIS fit, read
    from META alone (no shard bytes touched).  ``None`` when the
    checkpoint predates the elastic plane (no recorded ``world_size``)
    or the world is unchanged."""
    from ray_lightning_tpu.utils import sharded_ckpt

    try:
        extra = sharded_ckpt.load_meta(path).get("extra", {})
    except Exception:  # noqa: BLE001 - a corrupt META fails later, in
        # load_sharded, with the full verify story
        return None
    old_world = extra.get("world_size")
    if not old_world:
        return None
    old_world = int(old_world)
    recorded_accum = extra.get("accum")
    old_accum = int(recorded_accum or cfg_accum)
    if old_world == int(world_size):
        if recorded_accum is None or int(recorded_accum) == int(cfg_accum):
            return None
        # Same world, but the checkpoint's trajectory ran a DIFFERENT
        # accum — a previous elastic resize re-derived it (shrink at 2
        # writes world_size=1/accum=2; a later same-world crash resume
        # must not silently revert to the config's 1, which would both
        # change the global batch mid-trajectory and hand the
        # congruence-dependent reconciliations a structurally
        # mismatched opt_state).  The recorded value wins, loudly.
        return {
            "old_world": old_world,
            "new_world": int(world_size),
            "old_accum": int(recorded_accum),
            "accum": int(recorded_accum),
            "exact": True,
            "ckpt": path,
        }
    new_accum = _rederive_accum(old_world, old_accum, world_size)
    return {
        "old_world": old_world,
        "new_world": int(world_size),
        "old_accum": old_accum,
        "accum": new_accum if new_accum is not None else old_accum,
        "exact": new_accum is not None,
        "ckpt": path,
    }


def _reconcile_multisteps(host_state: Any, template: Any) -> Any:
    """Elastic accum re-derivation can cross the ``accum == 1``
    boundary, changing the opt_state WRAPPER: accum > 1 wraps the inner
    optimizer state in ``optax.MultiStepsState``.  A checkpoint from
    the other side of the boundary is re-wrapped here so the resumed
    tree stays congruent with this run's state template:

    * bare → MultiSteps (shrink drove accum past 1): fresh window —
      ``mini_step = 0``, zero ``acc_grads``, ``gradient_step`` carried
      from the train step counter;
    * MultiSteps → bare (grow collapsed accum to 1): the inner state is
      unwrapped; a PARTIAL accumulation window is dropped with a loud
      warning (its micro-grads never reached the params — at most
      ``accum - 1`` micro-batches of gradient signal).
    """
    import optax

    from ray_lightning_tpu.core.module import TrainState

    if not isinstance(host_state, TrainState) or not isinstance(
        template, TrainState
    ):
        return host_state
    have = isinstance(host_state.opt_state, optax.MultiStepsState)
    want = isinstance(template.opt_state, optax.MultiStepsState)
    if have == want:
        return host_state
    if want:
        step32 = np.asarray(
            jax.device_get(host_state.step), np.int32
        )
        ms = optax.MultiStepsState(
            mini_step=np.zeros((), np.int32),
            gradient_step=step32,
            inner_opt_state=host_state.opt_state,
            acc_grads=jax.tree_util.tree_map(
                lambda p: np.zeros(
                    getattr(p, "shape", ()),
                    getattr(p, "dtype", np.float32),
                ),
                jax.device_get(host_state.params),
            ),
        )
        return TrainState(
            host_state.params, ms, host_state.step,
            host_state.grad_residual,
        )
    ms = host_state.opt_state
    mini = int(np.asarray(jax.device_get(ms.mini_step)))
    if mini:
        import warnings

        warnings.warn(
            f"elastic resume collapsed accum to 1: the checkpoint's "
            f"partial accumulation window ({mini} micro-grad(s)) is "
            "dropped"
        )
    return TrainState(
        host_state.params, ms.inner_opt_state, host_state.step,
        host_state.grad_residual,
    )


def _reconcile_opt_state_format(host_state: Any, template: Any) -> Any:
    """Reconcile a checkpoint's optimizer-state STORAGE FORMAT with
    this run's template across an ``opt_state_dtype`` policy change
    (models/optim.py): quantized ↔ float moment leaves differ in tree
    STRUCTURE (a :class:`~ray_lightning_tpu.ops.optim_quant.BlockQuantized`
    node vs a bare array), which the dtype-cast reconciliation below
    cannot express.  Float → quantized requantizes (lossy by exactly
    the codec's rounding — the same rounding a fresh step would apply);
    quantized → float dequantizes.  Same-policy resumes pass through
    untouched, so int8 state round-trips drain → resume bit-exactly.
    """
    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.ops.optim_quant import (
        dequantize_moment,
        is_block_quantized,
        quantize_moment,
    )

    if not isinstance(host_state, TrainState) or not isinstance(
        template, TrainState
    ):
        return host_state
    tdef = jax.tree_util.tree_structure(template.opt_state)
    hdef = jax.tree_util.tree_structure(host_state.opt_state)
    if tdef == hdef:
        return host_state
    converted = [0]

    def coerce(tmpl_leaf, ckpt_piece):
        t_q = is_block_quantized(tmpl_leaf)
        c_q = is_block_quantized(ckpt_piece)
        if t_q and c_q:
            if (tuple(tmpl_leaf.shape) != tuple(ckpt_piece.shape)
                    or tmpl_leaf.block_size != ckpt_piece.block_size
                    or tmpl_leaf.sqrt_domain != ckpt_piece.sqrt_domain):
                converted[0] += 1
                return quantize_moment(
                    dequantize_moment(ckpt_piece),
                    block_size=tmpl_leaf.block_size,
                    sqrt_domain=tmpl_leaf.sqrt_domain,
                )
            return ckpt_piece
        if t_q:
            converted[0] += 1
            return quantize_moment(
                jnp.asarray(ckpt_piece, jnp.float32),
                block_size=tmpl_leaf.block_size,
                sqrt_domain=tmpl_leaf.sqrt_domain,
            )
        if c_q:
            converted[0] += 1
            return dequantize_moment(ckpt_piece).astype(
                getattr(tmpl_leaf, "dtype", jnp.float32)
            )
        return ckpt_piece

    try:
        new_opt = jax.tree_util.tree_map(
            coerce, template.opt_state, host_state.opt_state,
            is_leaf=is_block_quantized,
        )
    except ValueError:
        # Structures differ beyond moment storage (a genuinely foreign
        # checkpoint) — let the downstream congruence checks raise
        # their own, more specific error.
        return host_state
    if converted[0]:
        import warnings

        warnings.warn(
            f"resume across an opt_state_dtype change: "
            f"{converted[0]} optimizer moment leaves converted to this "
            "run's storage format (float ↔ block-scaled int8; "
            "requantization applies the codec's rounding once)"
        )
    return TrainState(
        host_state.params, new_opt, host_state.step,
        host_state.grad_residual,
    )


def _announce_resize(info: Dict[str, Any], tel: Telemetry, queue,
                     global_rank: int) -> None:
    """Make an elastic N→M resume LOUD: a warning on every rank, an
    ``elastic_resizes`` counter, and (rank 0) a schema-shaped ``resize``
    event on the driver queue — the old/new world sizes flow through
    the monitor into ``trainer.monitor_report``, OpenMetrics and
    ``rlt_top`` like every other recovery event."""
    import warnings

    from ray_lightning_tpu.telemetry.monitor import make_event

    if info["old_world"] == info["new_world"]:
        # No world change — an accum-continuity override (the recorded
        # accum beats the config's): warn, but no resize event.
        warnings.warn(
            f"elastic resume: honoring the checkpoint's recorded "
            f"accum {info['accum']} over the configured value — the "
            f"state's optimizer trajectory (and the global batch per "
            f"optimizer step) continues what a previous elastic "
            f"resize established"
        )
        return
    msg = (
        f"elastic resume: checkpoint from world size {info['old_world']}"
        f" (accum {info['old_accum']}) resuming on {info['new_world']}"
        f" with accum {info['accum']}"
    )
    if not info["exact"]:
        msg += (
            " — old_world*accum does not divide the new world size; the"
            " GLOBAL batch per optimizer step changes and the LR"
            " schedule is no longer step-equivalent"
        )
    warnings.warn(msg)
    tel.add_counter("elastic_resizes", 1)
    if queue is not None and global_rank == 0:
        try:
            queue.put(make_event(
                "resize", global_rank,
                old_world=info["old_world"],
                new_world=info["new_world"],
                message=msg, ckpt=info["ckpt"],
            ))
        except Exception:  # noqa: BLE001 - queue may be mid-teardown
            pass


def _log_lr(ctx: "LoopContext", lr_schedule) -> None:
    """Log the learning rate that the MOST RECENT optimizer step applied
    (Lightning's LearningRateMonitor convention).  An optax schedule is
    indexed by completed updates when the update is computed, so update
    ``k`` used ``schedule(k-1)``."""
    if lr_schedule is None:
        return
    ctx.log_metrics(
        {"lr": float(lr_schedule(max(ctx.global_step - 1, 0)))}
    )


class _RunningMeanLogs:
    """Bounded per-epoch accumulator for device-scalar step logs.

    Keeps ONE live device buffer per metric (a running sum updated
    eagerly each step) instead of one dict of device scalars per
    micro-batch: at 10k steps/epoch the list form is tens of thousands
    of live tiny buffers plus a large end-of-epoch host sync.  The sum
    is carried in f32 regardless of the logged dtype — a bf16 running
    sum would stop absorbing per-step increments once it exceeds ~256x
    their size (7-bit mantissa), silently biasing long-epoch means.

    Non-finite step values (a NaN loss spike, an inf grad-norm log) are
    EXCLUDED from the mean — one poisoned step must not turn the whole
    epoch metric into NaN silently.  The exclusion happens on-device
    (``isfinite`` + ``where``, no host sync per step); the count of
    skipped values surfaces as ``nonfinite_count`` after :meth:`result`
    so telemetry can make the poisoning loud instead of hidden.
    """

    def __init__(self) -> None:
        self._sum: Optional[Dict[str, Any]] = None
        self._cnt: Optional[Dict[str, Any]] = None
        self._n = 0
        self.nonfinite_count = 0  # populated by result()

    def update(self, logs: Dict[str, Any]) -> None:
        if self._sum is None:
            self._sum, self._cnt = {}, {}
            for k, v in logs.items():
                v32 = jnp.asarray(v).astype(jnp.float32)
                finite = jnp.isfinite(v32)
                self._sum[k] = jnp.where(finite, v32, 0.0)
                self._cnt[k] = finite.astype(jnp.float32)
        else:
            for k in self._sum:
                v32 = jnp.asarray(logs[k]).astype(jnp.float32)
                finite = jnp.isfinite(v32)
                self._sum[k] = self._sum[k] + jnp.where(finite, v32, 0.0)
                self._cnt[k] = self._cnt[k] + finite.astype(jnp.float32)
        self._n += 1

    def update_stride(self, sums: Dict[str, Any], cnts: Dict[str, Any],
                      n: int) -> None:
        """Fold a megastep stride's ON-DEVICE accumulation into the
        epoch mean: ``sums``/``cnts`` are the finite-filtered f32 sums
        and finite counts the fused scan already reduced over its ``n``
        inner steps (``make_multi_step`` aux) — same math as ``n``
        :meth:`update` calls, paid as one device add per metric per
        stride instead of one per micro-batch."""
        if self._sum is None:
            self._sum = {k: jnp.asarray(v) for k, v in sums.items()}
            self._cnt = {k: jnp.asarray(v) for k, v in cnts.items()}
        else:
            for k in self._sum:
                self._sum[k] = self._sum[k] + sums[k]
                self._cnt[k] = self._cnt[k] + cnts[k]
        self._n += n

    def result(self) -> Dict[str, float]:
        if self._sum is None:
            return {}
        host_sum, host_cnt = jax.device_get((self._sum, self._cnt))
        out: Dict[str, float] = {}
        nonfinite = 0
        for k, s in host_sum.items():
            c = float(host_cnt[k])
            nonfinite += self._n - int(round(c))
            # Every value non-finite: nothing to average — report NaN
            # (loudly wrong) rather than a fabricated 0.
            out[k] = float(s) / c if c else float("nan")
        self.nonfinite_count = nonfinite
        return out


class _AsyncLogFetch:
    """Log-cadence metrics WITHOUT the host sync.

    The old path ran ``ctx.log_metrics(jax.device_get(logs))`` every
    ``log_every_n_steps`` — a blocking device→host fence that serialized
    the dispatch pipeline at exactly the cadence users log at.  This
    helper starts a device→host copy at the boundary
    (``copy_to_host_async``) and CONSUMES it at the next boundary (by
    which point the producing step has long finished, so ``device_get``
    returns without waiting).  Consequence, documented in
    docs/OBSERVABILITY.md: mid-fit consumers of step-cadence
    ``callback_metrics`` (CSV step rows, tune reports) see values one
    log interval late; epoch-end :meth:`flush` drains the tail, so
    post-fit metrics are identical to the synchronous path.
    """

    def __init__(self, ctx: "LoopContext"):
        self._ctx = ctx
        self._pending: Optional[Tuple[Dict[str, Any], Dict[str, float]]] = (
            None
        )

    def schedule(self, logs: Dict[str, Any],
                 extra: Optional[Dict[str, Any]] = None) -> None:
        """Consume the previous boundary's logs, then start this one's
        copy.  ``extra`` carries side values captured NOW (the lr of
        the step just taken — possibly still a lazy device scalar) so
        they stay paired with these logs when they land; device values
        in it ride the same async copy as the logs."""
        self.flush()
        for v in (*logs.values(), *(extra or {}).values()):
            start = getattr(v, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:  # noqa: BLE001 - the flush-time
                    # device_get is always correct; async is a hint.
                    pass
        self._pending = (logs, dict(extra or {}))

    def flush(self) -> None:
        """Land any in-flight logs into the context's metrics.  Called
        at the next boundary, at epoch end (BEFORE epoch means are
        logged — stale step values must not overwrite them), and before
        a drain checkpoint snapshots callback_metrics."""
        if self._pending is None:
            return
        logs, extra = self._pending
        self._pending = None
        logs, extra = jax.device_get((logs, extra))
        self._ctx.log_metrics(logs)
        if extra:
            self._ctx.log_metrics(extra)


def init_train_state(
    module: TpuModule,
    tx,
    mesh,
    zero_stage: int,
    seed: int,
    use_preset: bool = True,
    shard_update: bool = False,
) -> Tuple[TrainState, Any]:
    """Build the (possibly ZeRO-sharded) initial train state.

    ``shard_update`` (the cross-replica sharded weight update,
    arXiv:2004.13336 — docs/PERFORMANCE.md "Optimizer-state precision &
    update sharding") annotates the OPTIMIZER state sharded over the
    batch axes while params stay replicated: on a pure-DP mesh the
    in/out shardings on the jitted step then act as sharding
    constraints on the update computation — GSPMD lowers the gradient
    all-reduce to reduce-scatter, each replica updates only its shard
    of the moments, and the new params all-gather back — so a
    replicated-optimizer mesh stops paying P× the update's HBM+wire
    traffic.  A no-op where ZeRO already shards (``zero_stage >= 1``).

    Params are initialized **on-device under jit** with the target
    shardings as ``out_shardings`` — a ZeRO-3 model never materializes
    unsharded anywhere (contrast: the reference ships full
    ``state_dict`` bytes to every worker, ``ray_ddp.py:339-353``).
    Determinism comes from the broadcast seed (≙ ``PL_GLOBAL_SEED``,
    reference ``ray_ddp.py:223``).
    """
    rng = jax.random.PRNGKey(seed)
    # Warm-start hook: a module with ``initial_params`` set (a host
    # pytree — e.g. weights imported from a torch/HF checkpoint,
    # utils/hf_import.py) starts the fit from those weights instead of
    # init_params(rng).  Passed as a jit ARGUMENT, never a closure
    # constant, so the arrays are transferred once, not baked into the
    # compiled executable.  The caller sets ``use_preset=False`` when a
    # resume checkpoint will overwrite the state anyway — shipping a
    # GPT-scale pytree to the mesh just to discard it is gigabytes of
    # wasted transfer per worker per restart.
    preset = getattr(module, "initial_params", None) if use_preset else None
    import collections.abc

    if preset is not None and isinstance(preset, collections.abc.Mapping):
        from ray_lightning_tpu.models.quant import is_quantized

        if is_quantized(preset):
            # int8 decode storage (models/quant.py) is inference-only:
            # the optimizer cannot step int8 weights, and silently
            # dequantizing would train an already-rounded model.
            raise ValueError(
                "initial_params are int8-quantized (decode storage); "
                "training needs the original float tree — keep it, or "
                "dequantize explicitly before warm-starting"
            )

    def make(r):
        params = module.init_params(r)
        return TrainState.create(params, tx)

    def make_from(params):
        return TrainState.create(params, tx)

    if mesh is None:
        if preset is not None:
            return make_from(jax.device_put(preset)), None
        return make(rng), None
    abstract = jax.eval_shape(make, rng)
    # The sharded-update path reuses the ZeRO-1 sharding computation —
    # stage 1 is exactly "optimizer state sharded, params replicated" —
    # but the run's SEMANTIC zero_stage stays 0 (grad-comm gating,
    # checkpoint metadata and module compute-path selection all key off
    # the semantic stage).
    sharding_stage = max(zero_stage, 1) if shard_update else zero_stage
    shardings = shardlib.state_shardings_for_module(
        module, abstract, mesh, sharding_stage
    )
    if preset is not None:
        placed = jax.device_put(preset, shardings.params)
        state = jax.jit(make_from, out_shardings=shardings)(placed)
    else:
        state = jax.jit(make, out_shardings=shardings)(rng)
    return state, shardings


def _place_batch(batch, mesh):
    if mesh is None:
        return batch
    return shardlib.make_global_batch(batch, mesh)


def _same_batch_shape(a: Any, b: Any) -> bool:
    """Structure + leaf-shape congruence — the stacking precondition."""
    ta, tb = jax.tree_util.tree_structure(a), jax.tree_util.tree_structure(b)
    if ta != tb:
        return False
    return all(
        getattr(x, "shape", None) == getattr(y, "shape", None)
        and getattr(x, "dtype", None) == getattr(y, "dtype", None)
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    )


def _grouped(loader, stack: int, stack_limit: Optional[int]):
    """Group a batch stream into megastep strides.

    Yields ``("stride", [b0..b{k-1}])`` for full shape-congruent groups
    of ``stack`` batches, ``("single", b)`` otherwise.  ``stack_limit``
    (a multiple of ``stack``, or ``None`` for unlimited) bounds the
    stream POSITION a stride may extend to: every batch emitted —
    strided or not — consumes budget, so a ragged-shape single slipping
    into the stream can never push a later stride across the
    limit/max_steps boundary the caller aligned the budget to.
    """
    if stack <= 1:
        for b in loader:
            yield ("single", b)
        return
    it = iter(loader)
    emitted = 0  # batches yielded so far == stream position of pending[0]
    pending: List[Any] = []
    while True:
        if stack_limit is not None and emitted + stack > stack_limit:
            # Stride budget exhausted: drain, then stream singles.
            for p in pending:
                yield ("single", p)
            emitted += len(pending)
            pending = []
            for b in it:
                yield ("single", b)
            return
        try:
            item = next(it)
        except StopIteration:
            for p in pending:  # partial tail → per-step fallback
                yield ("single", p)
            return
        if pending and not _same_batch_shape(pending[0], item):
            # Ragged boundary (last small batch, shape change): flush
            # what we have as singles; the newcomer may seed a stride.
            for p in pending:
                yield ("single", p)
            emitted += len(pending)
            pending = [item]
        else:
            pending.append(item)
        if len(pending) == stack:
            yield ("stride", pending)
            emitted += stack
            pending = []


def _prefetched(loader, place: Callable[[Any], Any], depth: int = 2,
                telemetry: Optional[Telemetry] = None, stack: int = 1,
                stack_limit: Optional[int] = None,
                place_stride: Optional[Callable[[list], Any]] = None):
    """Iterate ``loader`` with host→device placement running ``depth``
    batches ahead on a background thread.  Yields ``(placed, n)`` pairs:
    ``n == 1`` for ordinary batches, ``n == stack`` for megastep strides
    (``stack > 1``) — where the producer stacked ``stack`` host batches
    and shipped them as ONE device array via ``place_stride``.

    On TPU the step is async-dispatched, so the input pipeline is the
    first serial bottleneck: without prefetch every step pays the numpy
    slice + ``device_put`` latency on the critical path.  A thread is
    enough — placement releases the GIL during the host→HBM DMA.

    ``telemetry`` (producer-side accounting): total host→device
    placement seconds and batch count land in the counters, so the
    consumer's ``data_wait_ms`` (how long the LOOP stalled) can be read
    against how busy the producer actually was — a high place total with
    near-zero data wait means the prefetch depth is doing its job.

    Lifecycle: the generator's ``close()`` (run the loop's ``finally``
    — see ``run_fit``) signals the producer's stop event AND JOINS the
    thread, so a fit that raises mid-epoch (drain, chaos crash, user
    exception) never leaks an ``rlt-prefetch`` thread into the next
    attempt of an elastic respawn or the next fit of a tuner sweep.
    """
    import queue as pyqueue
    import threading

    grouped = _grouped(loader, stack, stack_limit)

    def _place(kind: str, payload: Any):
        if kind == "stride":
            return (place_stride(payload), len(payload))
        return (place(payload), 1)

    if depth < 1:
        yield from (_place(k, p) for k, p in grouped)
        return

    buf: pyqueue.Queue = pyqueue.Queue(maxsize=depth)
    stop = threading.Event()
    sentinel = object()
    errors: List[BaseException] = []

    def producer() -> None:
        try:
            for kind, payload in grouped:
                t0 = time.perf_counter()
                placed = _place(kind, payload)
                if telemetry is not None:
                    # Counter keys are producer-thread-private; the dict
                    # update itself is GIL-atomic.
                    telemetry.add_counter(
                        "prefetch_place_s", time.perf_counter() - t0
                    )
                    telemetry.add_counter("prefetch_batches", placed[1])
                while not stop.is_set():
                    try:
                        buf.put(placed, timeout=0.1)
                        break
                    except pyqueue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised on consumer
            errors.append(e)
        finally:
            while not stop.is_set():
                try:
                    buf.put(sentinel, timeout=0.1)
                    break
                except pyqueue.Full:
                    continue

    thread = threading.Thread(
        target=producer, name="rlt-prefetch", daemon=True
    )
    thread.start()
    try:
        while True:
            item = buf.get()
            if item is sentinel:
                if errors:
                    raise errors[0]
                return
            yield item
    finally:
        stop.set()
        # Join, don't just signal: "no thread left behind" is the
        # contract the leak-regression test pins (the producer's put
        # loop polls the stop event every 0.1s, so this is bounded).
        thread.join(timeout=5.0)


def _run_validation(
    module: TpuModule,
    eval_step,
    loader,
    ctx: LoopContext,
    limit: int,
) -> Dict[str, float]:
    acc = _RunningMeanLogs()
    for i, batch in enumerate(loader):
        if limit >= 0 and i >= limit:
            break
        acc.update(
            eval_step(ctx.state.params, _place_batch(batch, ctx.mesh))
        )
        ctx.progress += 1  # liveness: eval batches count as forward motion
    return acc.result()


_compile_cache_dir = [None]  # the dir this process last configured


def _enable_compile_cache() -> None:
    """Opt-in persistent XLA compilation cache (``RLT_COMPILE_CACHE``).

    Workers receive it as ``JAX_COMPILATION_CACHE_DIR`` before their
    first jax import (strategy env bus); this in-process hook covers the
    LocalStrategy/driver path, where jax is already imported and only
    ``jax.config`` still takes effect.  The knob tracks the env var in
    BOTH directions — unset it before a later fit and that fit really
    runs uncached (A/B attribution).  Any transition (on/off/dir change)
    also calls jax's ``reset_cache``: jax memoizes the cache decision
    and the cache object at the first compile, so flipping the config
    alone would silently keep using the previous directory.  Failures
    are non-fatal — the cache is an amortization, never a correctness
    dependency.
    """
    cache_dir = os.environ.get("RLT_COMPILE_CACHE") or None
    if cache_dir == _compile_cache_dir[0]:
        return
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache EVERY compile when on: the default ~1s threshold skips
        # "fast" compiles, but on the remote-TPU tunnel even those carry
        # multi-second dispatch latency, and a threshold makes tiny-step
        # caching nondeterministic (observed: the same fit caches or not
        # depending on host load).
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            0.0 if cache_dir else 1.0,
        )
        _compile_cache_dir[0] = cache_dir
    except Exception as e:  # noqa: BLE001 - best-effort amortization
        import warnings

        warnings.warn(f"RLT_COMPILE_CACHE ignored ({e})")


def run_fit(
    module: TpuModule,
    datamodule: TpuDataModule,
    config: FitConfig,
    callbacks: List[Callback],
    global_rank: int = 0,
    world_size: int = 1,
    mesh=None,
    mode: str = "gspmd",
    zero_stage: int = 0,
    grad_comm=None,
    telemetry=None,
    queue=None,
) -> Dict[str, Any]:
    """The full fit loop.  Returns the rank-0 result package.

    Result shape ≙ reference ``execute_remote``'s rank-0 return tuple
    (``ray_ddp.py:490-519``): state stream + callback metrics + best model
    path (+ callback states so driver-side callback objects reflect what
    happened remotely).  Every rank's package additionally carries its
    telemetry snapshot, so the driver can build the fleet-wide skew view
    (``trainer.telemetry_report``) — not just rank-0's numbers.

    Preemption (SIGTERM/SIGINT, a driver drain request, or the chaos
    plane's ``sigterm`` fault) does not crash the fit: the loop finishes
    the in-flight step, writes a step-granular drain checkpoint
    (``drain-step-*.ckpt``, sharded) and raises :class:`PreemptedError`
    — which the strategy converts into a budget-free elastic restart or
    a clean resumable raise (docs/FAULT_TOLERANCE.md).
    """
    _enable_compile_cache()
    # Graceful-drain arming: clear any previous fit's flag (inline
    # strategies run many fits per process), mark a fit as in flight so
    # SIGTERM means "drain" rather than "exit", and — on the driver's
    # main thread only; worker children install theirs in _child_main —
    # take over the signal handlers for the duration of the fit.
    drain_mod.reset_drain()
    drain_mod.set_fit_active(True)
    _signals_installed = drain_mod.install_signal_handlers()
    chaos.set_rank(global_rank)
    try:
        return _run_fit_inner(
            module, datamodule, config, callbacks, global_rank,
            world_size, mesh, mode, zero_stage, grad_comm, telemetry,
            queue,
        )
    finally:
        drain_mod.set_fit_active(False)
        if _signals_installed:
            drain_mod.uninstall_signal_handlers()


def _run_fit_inner(
    module: TpuModule,
    datamodule: TpuDataModule,
    config: FitConfig,
    callbacks: List[Callback],
    global_rank: int,
    world_size: int,
    mesh,
    mode: str,
    zero_stage: int,
    grad_comm,
    telemetry,
    queue,
) -> Dict[str, Any]:
    tx = module.configure_optimizers()
    # configure_optimizers may return (tx, lr_schedule); careful — a bare
    # optax.GradientTransformation is itself a NamedTuple, so test for the
    # optimizer interface rather than tuple-ness.
    lr_schedule = None
    if isinstance(tx, tuple) and not hasattr(tx, "init"):
        tx, lr_schedule = tx[0], (tx[1] if len(tx) > 1 else None)
    accum = max(int(config.accumulate_grad_batches), 1)
    # Elastic resume (reshard-on-load): a sharded checkpoint records the
    # world size and accumulation factor it was trained at; resuming on
    # a DIFFERENT world size re-derives accum here — before the
    # optimizer wraps in MultiSteps — so the global batch per optimizer
    # step (and therefore the LR schedule, which indexes optimizer
    # steps) is invariant under N→M.  Per-step RNG needs no such fix:
    # it folds the resumed micro-step into the base key
    # (``fold_in(base_rng, micro_step)`` below), which never saw the
    # world size.
    resize_info = None
    if config.resume_from_checkpoint:
        from ray_lightning_tpu.utils import sharded_ckpt as _sc

        if _sc.is_sharded_ckpt(config.resume_from_checkpoint):
            resize_info = _elastic_resume_info(
                config.resume_from_checkpoint, world_size, accum
            )
    if resize_info is not None:
        accum = resize_info["accum"]
    inner_tx = tx
    if accum > 1:
        import optax

        # MultiSteps keeps the grad accumulator inside opt_state, so ZeRO
        # sharding, donation and checkpointing all see it as ordinary
        # optimizer state (params-shaped ⇒ the suffix-matching sharding
        # rule reuses the parameter specs).
        tx = optax.MultiSteps(tx, every_k_schedule=accum)

    ctx = LoopContext(config, global_rank, world_size, mesh, queue, tx)
    ctx.step_mode = mode
    ctx.zero_stage = zero_stage
    module.trainer = ctx
    module.precision = config.precision

    # Telemetry: on by default at the cheap tier (counters + step stats);
    # spans/export engage at tier "full" (telemetry= / RLT_TELEMETRY).
    n_chips = len(mesh.devices.flat) if mesh is not None else 1
    tel = Telemetry.build(
        telemetry, global_rank, world_size, n_chips=n_chips
    )
    ctx.telemetry = tel
    ctx.telemetry_dir = (
        tel.export_dir_for(config.default_root_dir) if tel.enabled
        else None
    )
    tel_stats = tel.step_stats
    if tel_stats is not None:
        tel_stats.configure_model(module)
    if resize_info is not None:
        _announce_resize(resize_info, tel, queue, global_rank)

    # Live observability plane (docs/OBSERVABILITY.md "Live monitoring"):
    # a heartbeat publisher thread (queue sink on workers, JSONL sink on
    # queue-less local fits), a rank-tagged log ring, and the crash
    # flight recorder — armed here, disarmed on the success path below;
    # the stage wrappers route uncaught exceptions through
    # ``flight_recorder.record_active_crash``.  Tier "off" installs
    # nothing: no thread, no handler, no files.
    from ray_lightning_tpu.telemetry.flight_recorder import FlightRecorder
    from ray_lightning_tpu.telemetry.heartbeat import HeartbeatPublisher
    from ray_lightning_tpu.telemetry.logs import RankLogHandler

    log_handler = (
        RankLogHandler(global_rank, queue=queue).install()
        if tel.enabled else None
    )
    heartbeat = HeartbeatPublisher.maybe_start(tel, ctx, queue, config)
    flight_recorder = FlightRecorder.maybe_install(
        tel, ctx, queue, log_handler=log_handler, heartbeat=heartbeat,
    )

    module.setup("fit")
    datamodule.set_shard(global_rank, world_size)
    # prepare_data is per-HOST work (downloads land on each host's local
    # filesystem — one actor per host is this framework's deployment
    # model), so every worker runs it; implementations should be
    # idempotent/locked like the reference's init_hook FileLock pattern
    # (examples/ray_ddp_tune.py:22-25).
    datamodule.prepare_data()
    datamodule.setup("fit")
    _call_hooks(callbacks, "setup", ctx, module, "fit")

    # Gradient-communication coercion (str | dict | GradCommConfig | None
    # — None reads the RLT_GRAD_COMM env bus, defaulting to full-width).
    # Resolution happens against the REAL mesh/stage shape and warns on
    # every downgrade; modules consult ``trainer.grad_sync_active`` to
    # pick per-device-safe compute paths inside the sync island.
    from ray_lightning_tpu.parallel import grad_sync as gsync

    # Backward-overlapped sync: the resolved trunk-segment count G is
    # visible to the module's forward via the trainer context even when
    # grad_sync itself is off (grad_comm=full) — pure segmentation is
    # bitwise-neutral, so the knob's schedule shape can be A/B'd
    # independently of the wire codec.
    overlap_segments = resolve_grad_overlap(config.grad_overlap_segments)
    ctx.grad_overlap_segments = overlap_segments
    grad_sync = gsync.maybe_build_grad_sync(
        module, mesh, grad_comm, mode=mode, zero_stage=zero_stage,
        overlap_segments=overlap_segments,
    )
    ctx.grad_sync_active = grad_sync is not None
    tel.set_meta("grad_overlap_segments", overlap_segments)
    # Wire accounting flows through the telemetry counters (the unified
    # report) — ``ctx.comm_stats`` stays as a compatibility view of the
    # same numbers, not a parallel bookkeeping path.
    if grad_sync is not None:
        grad_sync.register_telemetry(tel)
        ctx.comm_stats = grad_sync.stats()
    else:
        tel.set_meta("grad_sync_mode", "full")
        ctx.comm_stats = {"grad_sync_mode": "full"}

    # Cross-replica sharded weight update: resolved against the real
    # mesh/mode/stage (docs/PERFORMANCE.md "Optimizer-state precision &
    # update sharding"); recorded in telemetry so bench artifacts can
    # attribute the arm.
    shard_update = _resolve_update_sharding(config, mesh, mode, zero_stage)
    tel.set_meta("update_sharding", "on" if shard_update else "off")
    ctx.update_sharding_active = shard_update
    state, state_shardings = init_train_state(
        module, tx, mesh, zero_stage, config.seed,
        use_preset=not config.resume_from_checkpoint,
        shard_update=shard_update,
    )
    if grad_sync is not None:
        # Error-feedback residual (int8_ef): attached to BOTH the state
        # and its sharding tree before the step compiles, so the jit's
        # in/out shardings stay congruent with the donated state.
        state, state_shardings = grad_sync.attach_residual(
            state, state_shardings
        )
    start_epoch = 0
    resume_skip_batches = 0
    if config.resume_from_checkpoint:
        from ray_lightning_tpu.utils import sharded_ckpt

        if sharded_ckpt.is_sharded_ckpt(config.resume_from_checkpoint):
            # Sharded restart checkpoint, reshard-on-load: with this
            # run's shardings the index-selective reader places each
            # leaf straight onto the M-device mesh, each host reading
            # only the shard-file byte ranges overlapping its own
            # addressable shards (no full-model reassembly on ZeRO-3).
            # A structure mismatch (EF residual present on one side
            # only) falls back to the full host read; either way resume
            # works on any topology, including fewer workers than
            # wrote it.
            payload = sharded_ckpt.load_sharded(
                config.resume_from_checkpoint,
                shardings=state_shardings,
            )
        else:
            payload = load_state_stream(
                state_stream_from_file(config.resume_from_checkpoint)
            )
        host_state = payload["state"]
        if grad_sync is not None:
            # A stream written without EF (or from another world size)
            # gets a fresh zero residual; one written with EF resuming
            # into a full-width run sheds it — either way the resumed
            # tree stays congruent with this run's state template.
            host_state = grad_sync.reconcile_resumed_state(host_state)
        elif getattr(host_state, "grad_residual", None) is not None:
            from ray_lightning_tpu.core.module import TrainState as _TS

            host_state = _TS(
                host_state.params, host_state.opt_state, host_state.step
            )
        if resize_info is not None:
            # Accum re-derivation may have crossed the accum==1
            # boundary (the optax.MultiSteps wrapper appears or
            # vanishes) — re-wrap before the congruence-dependent
            # reconciliations below.
            host_state = _reconcile_multisteps(host_state, state)
        # Storage-format reconcile: an ``opt_state_dtype`` policy change
        # between runs (f32/bf16 moments ↔ block-scaled int8) changes
        # the opt-state TREE STRUCTURE, not just leaf dtypes — convert
        # before the per-leaf cast below (which requires congruence).
        host_state = _reconcile_opt_state_format(host_state, state)
        # Reconcile checkpoint dtypes with THIS run's state template: a
        # dtype-policy change between runs (e.g. AdamW mu f32 → bf16,
        # models/gpt.py ``mu_dtype``) must not leak the old dtype into
        # the new run — it would silently recompile the step against a
        # mixed-dtype state and diverge from a fresh run's numerics.
        host_state = jax.tree_util.tree_map(
            lambda tmpl, leaf: leaf.astype(tmpl.dtype)
            if (
                hasattr(tmpl, "dtype")
                and hasattr(leaf, "astype")
                and tmpl.dtype != leaf.dtype
            )
            else leaf,
            state,
            host_state,
        )
        if mesh is None:
            state = jax.device_put(host_state)
        else:
            state = jax.device_put(host_state, state_shardings)
        if payload.get("mid_epoch"):
            # Step-granular drain checkpoint: resume INSIDE the epoch it
            # was written in, skipping the micro-batches already trained
            # (loaders are epoch-seeded, so the order replays exactly).
            start_epoch = payload["epoch"]
            resume_skip_batches = int(payload.get("batch_in_epoch", 0))
            if (resize_info is not None and world_size != 1
                    and resize_info["old_world"]
                    != resize_info["new_world"]):
                import warnings

                # Per-host loader shards are keyed off the world size:
                # under N→M the epoch's row→host partition changes, so
                # position-based skipping cannot replay the exact
                # global rows.  Counters stay step-exact; data replay
                # is exact only at equal world size (or world 1).
                warnings.warn(
                    "mid-epoch elastic resume at a different world "
                    "size: this epoch's remaining rows are re-sharded "
                    "over the new worker set — some rows may repeat "
                    "or be skipped within the epoch"
                )
        else:
            start_epoch = payload["epoch"] + 1
            resume_skip_batches = 0
        # If the checkpoint already covers max_epochs the loop body never
        # runs; current_epoch must still report the work as done.
        ctx.current_epoch = max(start_epoch - 1, 0)
        if "micro_step" in payload:
            ctx.global_step = payload["global_step"]
            ctx.micro_step = payload["micro_step"]
        else:
            # Legacy streams predate the optimizer-step convention: their
            # "global_step" stored the MICRO-batch count.
            ctx.micro_step = payload["global_step"]
            ctx.global_step = payload["global_step"] // accum
        ctx.callback_metrics.update(payload.get("callback_metrics", {}))
        # Stateful callbacks (EarlyStopping patience, ModelCheckpoint
        # best-score/path, …) continue rather than reset on resume.
        for cb, cb_state in zip(
            callbacks, payload.get("callback_states", [])
        ):
            cb.load_state_dict(cb_state)
    ctx.state = state

    params_shardings = (
        state_shardings.params if state_shardings is not None else None
    )
    train_step = step_fns.build_train_step(
        module, tx, mesh, mode=mode, zero_stage=zero_stage,
        state_shardings=state_shardings, grad_sync=grad_sync,
    )
    # Megastep execution: fuse K micro-steps into one lax.scan dispatch
    # (docs/PERFORMANCE.md "Host dispatch & megastep").  The single-step
    # jit above stays alive as the exact-semantics fallback for partial
    # strides (epoch/limit/max_steps boundaries) and pinned chaos
    # injections — jit is lazy, so an all-strides fit never compiles it
    # twice... and an all-singles fit never compiles the scan.
    megastep_k = _resolve_megastep(config)
    multi_step = (
        step_fns.make_multi_step(
            module, tx, mesh, megastep_k, mode=mode,
            zero_stage=zero_stage, state_shardings=state_shardings,
            grad_sync=grad_sync,
        )
        if megastep_k > 1 else None
    )
    tel.set_meta("megastep", megastep_k)

    def _place_stride(batches: List[Any]):
        """K host micro-batches → one stacked device array (leaf shape
        (K, B, ...)) — a single transfer per stride."""
        if mesh is None:
            return jax.device_put(shardlib.stack_host_batches(batches))
        return shardlib.make_global_stacked_batch(batches, mesh)
    val_loader = datamodule.val_dataloader()
    eval_step = (
        step_fns.build_eval_step(
            module, mesh, "validation", mode=mode,
            params_shardings=params_shardings,
        )
        if val_loader is not None
        else None
    )

    module.on_fit_start()
    _call_hooks(callbacks, "on_fit_start", ctx, module)

    base_rng = jax.random.PRNGKey(config.seed)
    train_loader = datamodule.train_dataloader()
    stop = False
    flush_step = None  # built lazily on the first partial-window flush
    # Preemption plumbing: the coordinated drain-agreement collective
    # (multi-process meshes only — None is the zero-overhead local path)
    # and the drain finish-line itself.
    drain_poll = _make_drain_poll(mesh, world_size)
    # Async log-cadence fetch (see _AsyncLogFetch): scheduled at log
    # boundaries, consumed one boundary later, flushed before anything
    # that snapshots callback_metrics (epoch means, drain META, and —
    # via ctx.pending_log_flush — the crash flight bundle).
    log_fetch = _AsyncLogFetch(ctx)
    ctx.pending_log_flush = log_fetch.flush

    def _graceful_drain(mid_epoch: bool, batch_in_epoch: int):
        """Preemption finish-line: write the step-granular sharded
        drain checkpoint, retire the live plane with an orderly final
        beat, and exit with the distinguished PreemptedError the
        strategy converts into a budget-free restart or a clean raise.
        COLLECTIVE on multi-host meshes (save_shard + barrier) — only
        reached after every rank agreed to drain at this same step."""
        from ray_lightning_tpu.utils import sharded_ckpt

        ctx.phase = "draining"
        try:
            # In-flight async log fetch lands BEFORE the META snapshot
            # of callback_metrics below.
            log_fetch.flush()
        except Exception:  # noqa: BLE001 - never cost the drain
            pass
        reason = drain_mod.drain_reason() or "requested"
        drain_dir = config.restart_dir or os.path.join(
            config.default_root_dir, "preempt"
        )
        tag = os.path.join(
            drain_dir, f"drain-step-{ctx.micro_step:08d}.ckpt"
        )
        t0 = time.perf_counter()
        ckpt_path = None
        write_err = None
        try:
            ctx.flush_checkpoints()
            sharded_ckpt.save_shard(
                ctx.state, tag, global_rank, world_size
            )
        except Exception as e:  # noqa: BLE001 - the checkpoint is
            # sacrificed, never the drain itself
            write_err = e
        # EVERY rank reaches the barrier, write success or not: a rank
        # skipping it (its disk filled, say) would strand its peers in
        # the collective for the whole grace window.  A failed shard
        # write still yields a META'd-but-incomplete checkpoint, which
        # restart discovery's verification walks past by design.
        try:
            _mesh_barrier(mesh)
        except Exception as e:  # noqa: BLE001 - a peer died mid-drain
            write_err = write_err or e
        if write_err is None:
            try:
                if ctx.is_global_zero:
                    sharded_ckpt.save_meta(
                        ctx.state, tag, world_size,
                        extra={
                            "epoch": ctx.current_epoch,
                            "global_step": ctx.global_step,
                            "micro_step": ctx.micro_step,
                            "mid_epoch": mid_epoch,
                            "batch_in_epoch": batch_in_epoch,
                            # Elastic-resume contract: the world size
                            # and accum this state was trained at, so a
                            # resume on M != N devices can re-derive
                            # accum for global-batch invariance.
                            "world_size": world_size,
                            "accum": accum,
                            "drain_reason": reason,
                            "callback_metrics": dict(
                                ctx.callback_metrics
                            ),
                            "callback_states": [
                                cb.state_dict() for cb in callbacks
                            ],
                        },
                    )
                ckpt_path = tag
            except Exception as e:  # noqa: BLE001
                write_err = e
        if write_err is not None:
            import warnings

            warnings.warn(f"drain checkpoint write failed ({write_err!r})")
        drain_s = round(time.perf_counter() - t0, 4)
        tel.set_counter("drain_checkpoint_s", drain_s)
        if queue is not None:
            try:
                queue.put({
                    "type": "event", "kind": "drain",
                    "rank": global_rank, "ts": time.time(),
                    "message": (
                        f"rank {global_rank} drained on {reason} at "
                        f"micro_step {ctx.micro_step}"
                    ),
                    "ckpt": ckpt_path or "",
                })
            except Exception:  # noqa: BLE001 - queue may be mid-teardown
                pass
        # Final "done" beat: the monitor must read the coming silence
        # as an orderly exit, not flag a lost rank.
        if heartbeat is not None:
            heartbeat.stop(final=True)
        if flight_recorder is not None:
            flight_recorder.uninstall()
        if log_handler is not None:
            log_handler.uninstall()
        raise PreemptedError(
            f"fit preempted ({reason}) at micro_step {ctx.micro_step}; "
            + (f"drain checkpoint: {ckpt_path}" if ckpt_path
               else "no drain checkpoint could be written"),
            checkpoint=ckpt_path, step=ctx.micro_step,
            epoch=ctx.current_epoch, rank=global_rank, reason=reason,
            drain_s=drain_s,
        )

    # Agreement cadence: the multi-process poll is a collective whose
    # device_get would serialize host and device if run per step (the
    # overhead the telemetry sampler explicitly refuses to add), so it
    # runs every K micro-steps — K is a pure function of the shared
    # step counter, keeping every rank's collective call count aligned.
    # Worst-case drain latency is K steps, trivially inside any real
    # preemption grace window.  Single-process fits check the local
    # flag every step for free.
    drain_sync_every = max(
        int(os.environ.get("RLT_DRAIN_SYNC_EVERY", "8") or 8), 1
    )

    def _drain_agreed(local_wanted: bool = True,
                      sync_round: bool = True) -> bool:
        """One coordinated drain-agreement round.  Called at identical
        loop positions on every rank (the collective inside must line
        up across processes — ``sync_round`` must be identical fleet-
        wide at each call site)."""
        local = drain_mod.drain_requested() and local_wanted
        if drain_poll is not None:
            if not sync_round:
                return False  # off-cadence: no collective, no drain
            return drain_poll(local)
        return local
    # Host-side mirror of MultiSteps' window position: micro-batches since
    # the last optimizer update.  `micro_step % accum` is NOT equivalent
    # once a partial-window flush has reset the window mid-cycle.
    since_update = 0
    if config.resume_from_checkpoint and accum > 1:
        try:
            since_update = int(
                jax.device_get(ctx.state.opt_state.mini_step)
            )
        except AttributeError:
            since_update = ctx.micro_step % accum
    # First-use jit compiles of the two train programs (the fused scan
    # and the per-step fallback) can land MID-fit under megastep — a
    # partial tail stride or a chaos-degraded stride compiles the lazy
    # single-step program while progress is frozen for 20-40s at scale.
    # Flag those dispatches as a "compile" phase flip so the monitor's
    # per-phase exemption (telemetry/monitor.py) disarms the stall
    # watchdog instead of raising a false hang on a healthy rank.
    compiled_kinds: set = set()
    for epoch in range(start_epoch, config.max_epochs):
        ctx.current_epoch = epoch
        ctx.phase = "train"
        if hasattr(train_loader, "set_epoch"):
            train_loader.set_epoch(epoch)
        module.on_train_epoch_start(epoch)
        _call_hooks(callbacks, "on_train_epoch_start", ctx, module)

        epoch_mean = _RunningMeanLogs()
        # Mid-epoch drain resume: skip the micro-batches the drained run
        # already trained this epoch (the loader is epoch-seeded, so the
        # order replays identically); batch_idx stays ABSOLUTE within
        # the epoch so the limit checks below keep their meaning.
        skip = resume_skip_batches if epoch == start_epoch else 0
        # Cap the source BEFORE prefetching so the producer thread never
        # device-places batches past the limit/max_steps boundary.  The
        # +1 keeps one sentinel batch flowing so the in-loop checks (which
        # own the stop semantics) still observe the boundary crossing.
        cap = (
            max(config.limit_train_batches - skip, 0)
            if config.limit_train_batches >= 0 else None
        )
        if config.max_steps >= 0:
            # max_steps counts optimizer steps; the loop (and the cap)
            # run in micro-batches.  Position within the current window
            # comes from since_update (flushes reset it mid-cycle).
            remaining = max(
                (config.max_steps - ctx.global_step) * accum - since_update,
                0,
            )
            cap = remaining if cap is None else min(cap, remaining)
        src = iter(train_loader)
        if skip:
            src = itertools.islice(src, skip, None)
        source = src if cap is None else itertools.islice(src, cap + 1)
        # Megastep stride budget: only full K-strides lying ENTIRELY
        # inside the cap are fused (a multiple of K); the remainder —
        # partial strides at epoch/limit/max_steps boundaries — ships
        # per-step, so the in-loop boundary checks keep exact
        # "max_steps means max_steps" semantics.
        if megastep_k > 1:
            stack_limit = (
                None if cap is None else (cap // megastep_k) * megastep_k
            )
        else:
            stack_limit = 0
        last_logs: Dict[str, Any] = {}
        last_batch_idx = -1
        batch_idx = skip - 1  # absolute index of the last COMPLETED batch
        # Telemetry marks: ``t_mark`` is set at the end of each loop body,
        # so the gap to the next batch's arrival is exactly the time spent
        # blocked on the (prefetched) input pipeline — data_wait.
        t_mark = time.perf_counter()
        tracer = tel.tracer
        items = _prefetched(
            source, lambda b: _place_batch(b, mesh),
            telemetry=tel if tel.enabled else None,
            stack=megastep_k, stack_limit=stack_limit,
            place_stride=_place_stride,
        )
        try:
            for gbatch, n_inner in items:
                t_ready = time.perf_counter()
                if (
                    config.limit_train_batches >= 0
                    and batch_idx + 1 >= config.limit_train_batches
                ):
                    break
                # Check BEFORE executing: max_steps=0 trains zero steps.
                if (
                    config.max_steps >= 0
                    and ctx.global_step >= config.max_steps
                ):
                    stop = True
                    break
                if n_inner > 1 and chaos.step_fault_in_range(
                    ctx.micro_step, ctx.micro_step + n_inner,
                    epoch=epoch, rank=global_rank,
                ):
                    # A step-pinned chaos fault lands inside this stride:
                    # lower K to 1 around the injection — run the already
                    # -stacked micro-batches singly (device slices) so
                    # the fault fires at its exact inner-step index.
                    sub = [
                        (jax.tree_util.tree_map(
                            lambda x, j=j: x[j], gbatch), 1)
                        for j in range(n_inner)
                    ]
                else:
                    sub = [(gbatch, n_inner)]
                for gb, n in sub:
                    prev_micro = ctx.micro_step
                    # First use of either train program compiles inside
                    # the dispatch call below (host-blocking): flip the
                    # heartbeat phase so the monitor's per-phase stall
                    # arming (telemetry/monitor.py) treats the freeze as
                    # a compile, not a hang.
                    kind = "single" if n == 1 else "fused"
                    first_use = kind not in compiled_kinds
                    if first_use:
                        compiled_kinds.add(kind)
                        ctx.phase = "compile"
                    if n == 1:
                        # -- per-step path (exact boundary semantics) ----
                        # Chaos injection point: crash/hang/slow/sigterm
                        # pinned to (micro_step, epoch, rank) — near-zero
                        # cost unless RLT_FAULT is set.
                        chaos.fire("step", step=ctx.micro_step,
                                   epoch=epoch, rank=global_rank)
                        rng = jax.random.fold_in(base_rng, ctx.micro_step)
                        t_disp = time.perf_counter()
                        ctx.state, logs = train_step(ctx.state, gb, rng)
                        t_disp_end = time.perf_counter()
                        # Periodic device sampling: make THIS step's wall
                        # time include device execution (async dispatch
                        # hides it otherwise).  Never per-step — that
                        # would serialize host and device and become the
                        # overhead telemetry promises not to add.
                        sampled = (tel_stats is not None
                                   and tel_stats.should_sample())
                        if sampled:
                            jax.block_until_ready(logs)
                        epoch_mean.update(logs)
                        ctx.micro_step += 1
                        ctx.progress += 1  # heartbeat liveness counter
                        since_update += 1
                        if since_update == accum:
                            ctx.global_step += 1  # optimizer step done
                            since_update = 0
                        batch_idx += 1
                    else:
                        # -- megastep stride: ONE dispatch, n micro-steps
                        # fused in a lax.scan, metrics accumulated on
                        # device; the host does integer bookkeeping only.
                        t_disp = time.perf_counter()
                        ctx.state, saux = multi_step(
                            ctx.state, gb, base_rng,
                            np.int32(ctx.micro_step),
                        )
                        t_disp_end = time.perf_counter()
                        sampled = (
                            tel_stats is not None
                            and tel_stats.should_sample_stride(n)
                        )
                        if sampled:
                            jax.block_until_ready(saux)
                        epoch_mean.update_stride(
                            saux["sum"], saux["cnt"], n
                        )
                        logs = saux["last"]
                        ctx.micro_step += n
                        ctx.progress += n
                        since_update += n
                        ctx.global_step += since_update // accum
                        since_update %= accum
                        batch_idx += n
                        tel.add_counter("megastep_dispatches", 1)
                    tel.add_counter("train_dispatches", 1)
                    if ctx.phase == "compile":
                        ctx.phase = "train"
                    # Log cadence: identical to the old `% == 0` on the
                    # per-step path; a stride rounds the boundary to its
                    # end (stride-final logs).  The fetch is ASYNC —
                    # copy-to-host starts here, lands at the next
                    # boundary/epoch end — so logging never serializes
                    # host and device (docs/OBSERVABILITY.md).
                    n_log = config.log_every_n_steps
                    if n_log and drain_mod.sync_point_crossed(
                        prev_micro, ctx.micro_step, n_log
                    ):
                        extra = (
                            # Lazily-enqueued device scalar: the fetch
                            # materializes it at the NEXT boundary, so
                            # logging lr never fences the just-dispatched
                            # train program (a float() here would).
                            {"lr": lr_schedule(
                                max(ctx.global_step - 1, 0))}
                            if lr_schedule is not None else None
                        )
                        log_fetch.schedule(logs, extra)
                    _call_hooks(
                        callbacks, "on_train_batch_end", ctx, module,
                        logs, batch_idx,
                    )
                    last_logs, last_batch_idx = logs, batch_idx
                    t_end = time.perf_counter()
                    if tel_stats is not None:
                        leaves = jax.tree_util.tree_leaves(gb)
                        shape = (getattr(leaves[0], "shape", None)
                                 if leaves else None)
                        if n == 1:
                            tel_stats.record_step(
                                step_s=t_end - t_mark,
                                data_wait_s=t_ready - t_mark,
                                dispatch_s=t_disp_end - t_disp,
                                examples=int(shape[0]) if shape else 1,
                                sampled=sampled, compiled=first_use,
                            )
                        else:
                            tel_stats.record_stride(
                                stride_s=t_end - t_mark,
                                data_wait_s=t_ready - t_mark,
                                dispatch_s=t_disp_end - t_disp,
                                examples=(
                                    int(shape[0]) * int(shape[1])
                                    if shape and len(shape) > 1 else n
                                ),
                                k=n, sampled=sampled, compiled=first_use,
                            )
                        if first_use and n == 1:
                            # Roofline cross-check, once per program:
                            # feed the XLA cost_analysis FLOPs the
                            # ledger captured for the program that just
                            # compiled back into StepStats — MFU flips
                            # to a measured basis, and the drift guard
                            # flags a stale analytic accounting (>10%
                            # disagreement).  Fused megasteps are
                            # excluded: XLA costs the scanned body
                            # trip-count-agnostically, which would
                            # poison a per-example basis.
                            flops = (
                                program_ledger.ledger()
                                .site_flops_latest("train/step")
                            )
                            if flops:
                                tel_stats.configure_measured_flops(
                                    flops / max(
                                        int(shape[0]) if shape else 1, 1
                                    )
                                )
                    if tracer.enabled:
                        tracer.record(
                            "data_wait", t_mark, t_ready - t_mark
                        )
                        tracer.record(
                            "compile" if first_use
                            else ("megastep" if n > 1 else "dispatch"),
                            t_disp, t_disp_end - t_disp,
                        )
                    t_mark = t_end
                    # Chaos-degraded slices after the first: the data was
                    # already resident, only the first slice paid wait.
                    t_ready = t_mark
                    # Drain agreement (mesh-coordinated): a SIGTERM on
                    # ANY rank drains every rank at the same boundary.
                    # The multi-process collective runs whenever the
                    # advance crossed the K-step sync cadence (micro_step
                    # is identical across ranks, strides are config-
                    # deterministic — call counts stay aligned);
                    # single-process fits poll the local flag for free.
                    if _drain_agreed(
                        sync_round=drain_mod.sync_point_crossed(
                            prev_micro, ctx.micro_step, drain_sync_every
                        )
                    ):
                        _graceful_drain(
                            mid_epoch=True, batch_in_epoch=batch_idx + 1
                        )
        finally:
            # Deterministic producer shutdown: signal + JOIN the
            # rlt-prefetch thread even when the body raised (drain,
            # chaos, user exception) — a leaked producer would survive
            # into the next elastic attempt / tuner fit.
            items.close()

        # Flush a partial accumulation window (Lightning semantics: the
        # last incomplete window of an epoch still steps, from the mean
        # of the micro-grads seen).  Skipped when stopping at max_steps —
        # that contract promises exactly max_steps optimizer updates.
        if (
            accum > 1
            and not stop
            and int(jax.device_get(ctx.state.opt_state.mini_step)) > 0
        ):
            if flush_step is None:
                flush_step = _build_accum_flush(
                    inner_tx, mesh, state_shardings
                )
            ctx.state = flush_step(ctx.state)
            ctx.global_step += 1
            since_update = 0  # the flush reset MultiSteps' window
            # The flush IS an optimizer step: step-cadence callbacks
            # (EMA shadow updates) must observe it — via the dedicated
            # on_accumulation_flush hook, NOT a re-broadcast of
            # on_train_batch_end, which would double-fire batch-cadence
            # side effects (CSV rows, tune reports) for an event they
            # already saw.  Without this, the final epoch's flushed
            # update never entered the EMA average.
            _call_hooks(
                callbacks, "on_accumulation_flush", ctx, module,
                last_logs, last_batch_idx,
            )

        # Land the tail of the async log fetch BEFORE the epoch means:
        # a stale step value arriving later would overwrite them.
        log_fetch.flush()
        train_metrics = epoch_mean.result()
        ctx.log_metrics(train_metrics)
        _log_lr(ctx, lr_schedule)
        if tel.enabled:
            # NaN/inf step logs were excluded from the epoch means above;
            # surface the count so the exclusion is loud, not silent.
            if epoch_mean.nonfinite_count:
                tel.add_counter(
                    "nonfinite_logs", epoch_mean.nonfinite_count
                )
            # Headline telemetry rides callback_metrics on every plain
            # fit (step_time_ms, data_wait_ms, examples_per_sec, mfu…).
            ctx.log_metrics(tel.headline_metrics())
        module.on_train_epoch_end(epoch, train_metrics)

        # -- validation ----------------------------------------------------
        if (
            eval_step is not None
            and (epoch + 1) % config.check_val_every_n_epoch == 0
        ):
            ctx.phase = "validation"
            with tel.span("validation", epoch=epoch):
                val_metrics = _run_validation(
                    module, eval_step, val_loader, ctx,
                    config.limit_val_batches,
                )
            ctx.phase = "train"
            ctx.log_metrics(val_metrics)
            module.on_validation_epoch_end(val_metrics)
            _call_hooks(callbacks, "on_validation_epoch_end", ctx, module)

        _call_hooks(callbacks, "on_train_epoch_end", ctx, module)

        # Elastic-restart checkpoint — SHARDED, no all-gather: each host
        # writes only its addressable shards (utils/sharded_ckpt.py), so a
        # ZeRO-3 run's restart cost stays O(state/hosts) per host instead
        # of replicating the world every restart_every_n_epochs.
        if (
            config.restart_dir
            and (epoch + 1) % (config.restart_every_n_epochs or 1) == 0
        ):
            from ray_lightning_tpu.utils import sharded_ckpt

            tag = os.path.join(
                config.restart_dir, f"restart-epoch-{epoch:06d}.ckpt"
            )
            sharded_ckpt.save_shard(
                ctx.state, tag, global_rank, world_size
            )
            # Barrier before the completeness marker: META must only
            # appear once every host's shard file is durable.
            _mesh_barrier(mesh)
            if ctx.is_global_zero:
                sharded_ckpt.save_meta(
                    ctx.state, tag, world_size,
                    extra={
                        "epoch": ctx.current_epoch,
                        "global_step": ctx.global_step,
                        "micro_step": ctx.micro_step,
                        "world_size": world_size,
                        "accum": accum,
                        "callback_metrics": dict(ctx.callback_metrics),
                        "callback_states": [
                            cb.state_dict() for cb in callbacks
                        ],
                    },
                )
                # Keep the newest TWO complete checkpoints (this one +
                # its predecessor): previous-good fallback needs a
                # predecessor to fall back TO when the newest turns out
                # corrupt at resume time.  Anything older is disk growth.
                _prune_restart_dir(config.restart_dir, keep=2)

        # Stream per-epoch metrics to the driver (live callback_metrics on
        # the driver trainer — extends the reference, which only streamed
        # via Tune callbacks).
        if queue is not None and ctx.is_global_zero:
            # ``rank`` rides along so the driver can refuse metric
            # updates from anything but rank 0 (Trainer._on_stream_item
            # routes by type AND origin — a buggy/rogue worker must not
            # clobber driver metrics).
            queue.put(
                {
                    "type": "metrics",
                    "rank": ctx.global_rank,
                    "epoch": epoch,
                    "metrics": dict(ctx.callback_metrics),
                }
            )

        # Epoch-boundary drain point: a request that landed during
        # validation (or between epochs) is honored here — unless the
        # fit is finishing anyway, in which case completing IS the
        # cleanest drain.  `more_epochs` is identical on every rank
        # (config + mesh-global should_stop), keeping the agreement
        # collective aligned.
        more_epochs = (epoch + 1) < config.max_epochs and not (
            stop or ctx.should_stop
        )
        if _drain_agreed(local_wanted=more_epochs):
            _graceful_drain(mid_epoch=False, batch_in_epoch=0)

        if stop or ctx.should_stop:
            break

    # "closing": no step progress from here on is LEGITIMATE (flush,
    # final gather, serialization) — the RunMonitor exempts this phase
    # from stall flagging; the phase change itself counts as progress.
    ctx.phase = "closing"
    # Every async checkpoint write must be durable (and any failure
    # raised) BEFORE on_fit_end consumers run — the standard
    # load-best-at-fit-end pattern reads best_model_path there.
    ctx.flush_checkpoints()
    module.on_fit_end()
    _call_hooks(callbacks, "on_fit_end", ctx, module)
    ctx.close_checkpoint_writer()
    module.teardown("fit")
    _call_hooks(callbacks, "teardown", ctx, module, "fit")
    datamodule.teardown("fit")

    # -- rank-0 result package (≙ ray_ddp.py:490-519) -----------------------
    # The gather is collective: every rank participates, then only rank 0
    # serializes and ships the bytes.
    gathered = ctx._gathered_state()
    _maybe_export_telemetry(tel, ctx.telemetry_dir)
    # Retire the live plane on the success path: a final "done" beat so
    # the monitor reads the coming silence as completion (not a hang),
    # then disarm the crash recorder and the log ring.
    if heartbeat is not None:
        heartbeat.stop(final=True)
    if flight_recorder is not None:
        flight_recorder.uninstall()
    if log_handler is not None:
        log_handler.uninstall()
    # Snapshots ride EVERY rank's package (small dicts), so the driver
    # can aggregate min/max/mean across the fleet, not just rank 0.
    tel_snapshot = tel.snapshot()
    if not ctx.is_global_zero:
        return {"rank": global_rank, "telemetry": tel_snapshot}
    best_path = ""
    for cb in callbacks:
        if isinstance(cb, ModelCheckpoint):
            best_path = cb.best_model_path
            break
    return {
        "rank": 0,
        "state_stream": to_state_stream(gathered),
        "callback_metrics": {
            k: float(v) for k, v in ctx.callback_metrics.items()
        },
        "logged_metrics": {
            k: float(v) for k, v in ctx.logged_metrics.items()
        },
        "best_model_path": best_path,
        "callback_states": [cb.state_dict() for cb in callbacks],
        "epochs_run": ctx.current_epoch + 1,
        "global_step": ctx.global_step,
        "micro_step": ctx.micro_step,
        "comm_stats": dict(ctx.comm_stats),
        "telemetry": tel_snapshot,
    }


def _resolve_params(
    module: TpuModule,
    config: FitConfig,
    mesh,
    params_stream: Optional[bytes],
    ckpt_path: Optional[str],
    zero_stage: int = 0,
):
    """Parameter source for fit-less eval/predict (≙ test-without-fit,
    reference ``test_ddp_sharded.py:108-116``).

    Placement honors the module's TP specs and ZeRO-3 param sharding —
    a sharded model is never replicated onto every device just to eval
    (returns ``(params, params_shardings)``; shardings are ``None`` off
    -mesh).
    """
    if ckpt_path:
        payload = load_state_stream(state_stream_from_file(ckpt_path))
        host_params = payload["state"].params
    elif params_stream is not None:
        host_params = load_state_stream(params_stream)
    else:
        host_params = None
    if mesh is None:
        if host_params is None:
            params = jax.jit(module.init_params)(
                jax.random.PRNGKey(config.seed)
            )
        else:
            params = jax.device_put(host_params)
        return params, None
    abstract = (
        jax.eval_shape(module.init_params, jax.random.PRNGKey(config.seed))
        if host_params is None
        else jax.eval_shape(lambda: host_params)
    )
    shardings = shardlib.params_shardings_for_module(
        module, abstract, mesh, zero_stage
    )
    if host_params is None:
        params = jax.jit(
            module.init_params, out_shardings=shardings
        )(jax.random.PRNGKey(config.seed))
    else:
        params = jax.device_put(host_params, shardings)
    return params, shardings


def run_eval(
    module: TpuModule,
    datamodule: TpuDataModule,
    config: FitConfig,
    callbacks: List[Callback],
    kind: str = "validation",
    global_rank: int = 0,
    world_size: int = 1,
    mesh=None,
    mode: str = "gspmd",
    zero_stage: int = 0,
    params_stream: Optional[bytes] = None,
    ckpt_path: Optional[str] = None,
    telemetry=None,
    queue=None,
) -> Dict[str, Any]:
    """Validation/test loop (≙ reference ``start_evaluating``,
    ``ray_ddp.py:283-286``)."""
    _enable_compile_cache()
    stage = "validate" if kind == "validation" else "test"
    ctx = LoopContext(config, global_rank, world_size, mesh, queue)
    ctx.step_mode = mode
    ctx.zero_stage = zero_stage
    module.trainer = ctx
    n_chips = len(mesh.devices.flat) if mesh is not None else 1
    tel = Telemetry.build(
        telemetry, global_rank, world_size, n_chips=n_chips
    )
    ctx.telemetry = tel
    ctx.telemetry_dir = (
        tel.export_dir_for(config.default_root_dir) if tel.enabled
        else None
    )
    module.setup(stage)
    datamodule.set_shard(global_rank, world_size)
    datamodule.setup(stage)
    _call_hooks(callbacks, "setup", ctx, module, stage)

    params, params_shardings = _resolve_params(
        module, config, mesh, params_stream, ckpt_path, zero_stage
    )
    ctx.state = TrainState(params, None, 0)

    loader = (
        datamodule.val_dataloader()
        if kind == "validation"
        else datamodule.test_dataloader()
    )
    if loader is None:
        raise ValueError(f"datamodule provides no {kind} dataloader")
    eval_step = step_fns.build_eval_step(
        module, mesh, kind, mode=mode, params_shardings=params_shardings
    )
    with tel.span("validation", kind=kind):
        metrics = _run_validation(
            module, eval_step, loader, ctx, config.limit_val_batches
        )
    ctx.log_metrics(metrics)
    module.teardown(stage)
    _call_hooks(callbacks, "teardown", ctx, module, stage)
    _maybe_export_telemetry(tel, ctx.telemetry_dir)
    if not ctx.is_global_zero:
        return {"rank": global_rank, "telemetry": tel.snapshot()}
    return {
        "rank": 0,
        "callback_metrics": metrics,
        "telemetry": tel.snapshot(),
    }


def run_predict(
    module: TpuModule,
    datamodule: TpuDataModule,
    config: FitConfig,
    global_rank: int = 0,
    world_size: int = 1,
    mesh=None,
    zero_stage: int = 0,
    params_stream: Optional[bytes] = None,
    ckpt_path: Optional[str] = None,
    telemetry=None,
) -> Dict[str, Any]:
    """Prediction loop (≙ reference ``start_predicting``, ``ray_ddp.py:287-289``).

    Every worker returns its host-local output shards; the driver
    concatenates in rank order (an upgrade over the reference, which only
    returned rank-0 results).
    """
    _enable_compile_cache()
    tel = Telemetry.build(
        telemetry, global_rank, world_size,
        n_chips=len(mesh.devices.flat) if mesh is not None else 1,
    )
    module.setup("predict")
    datamodule.set_shard(global_rank, world_size)
    datamodule.setup("predict")
    params, params_shardings = _resolve_params(
        module, config, mesh, params_stream, ckpt_path, zero_stage
    )
    predict_step = step_fns.build_predict_step(
        module, mesh, params_shardings=params_shardings
    )
    loader = datamodule.predict_dataloader() or datamodule.test_dataloader()
    if loader is None:
        raise ValueError("datamodule provides no predict/test dataloader")

    outputs: List[np.ndarray] = []
    for batch in loader:
        with tel.span("dispatch"):
            out = predict_step(params, _place_batch(batch, mesh))
        # Host-local rows only: each host contributes its addressable
        # shards (its own slice of the global batch), ordered by shard
        # index so rows stay in loader order within the host.
        with tel.span("host_transfer"):
            if mesh is not None and world_size > 1:
                shards = sorted(
                    out.addressable_shards,
                    key=lambda s: s.index[0].start or 0,
                )
                local = [s.data for s in shards]
                outputs.append(np.concatenate(jax.device_get(local)))
            else:
                outputs.append(np.asarray(jax.device_get(out)))
    module.teardown("predict")
    _maybe_export_telemetry(
        tel, tel.export_dir_for(config.default_root_dir)
        if tel.enabled else None,
    )
    # Per-batch arrays (NOT pre-concatenated): each global batch is split
    # host-contiguously by NumpyLoader, so the driver must interleave
    # ranks batch-by-batch to recover dataset row order.
    return {
        "rank": global_rank,
        "prediction_batches": outputs,
        "telemetry": tel.snapshot(),
    }
