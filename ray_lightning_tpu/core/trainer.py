"""Trainer — the driver-side facade (≙ ``pl.Trainer`` as the reference uses it).

The user surface mirrors the reference's cardinal usage contract
(``/root/reference/README.md:50-62``): construct a Trainer with a strategy
(``plugins=[RayPlugin(...)]`` also accepted for drop-in familiarity), call
``fit(module, datamodule)``, and afterwards read ``trainer.callback_metrics``
/ ``trainer.best_model_path`` / the trained parameters — all recovered from
rank-0's result package exactly like the reference's ``post_dispatch``
(``ray_ddp.py:362-401``).

Driver discipline (≙ ``DelayedGPUAccelerator``, reference ``util.py:11-37``):
with a remote strategy the driver process never touches an accelerator —
model shipping, queue pumping and state recovery are pure-CPU work, so a
CPU-only laptop can drive a TPU pod.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ray_lightning_tpu.core.callbacks import Callback, ModelCheckpoint
from ray_lightning_tpu.core.data import TpuDataModule
from ray_lightning_tpu.core.loop import FitConfig
from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.utils.state_stream import load_state_stream

__all__ = ["Trainer"]


class _ModuleDataModule(TpuDataModule):
    """Adapter: modules may provide their own dataloaders (Lightning-style)."""

    def __init__(self, module: TpuModule):
        super().__init__()
        self._module = module

    def _sharded(self, loader):
        # Propagate the host shard to module-built loaders — without this a
        # multi-worker run would feed every host identical rows (violating
        # the DistributedSampler contract, reference ray_ddp.py:556-561).
        if loader is not None and hasattr(loader, "set_shard"):
            loader.set_shard(self.shard_index, self.num_shards)
        return loader

    def train_dataloader(self):
        return self._sharded(self._module.train_dataloader())

    def val_dataloader(self):
        fn = getattr(self._module, "val_dataloader", None)
        return self._sharded(fn()) if fn is not None else None

    def test_dataloader(self):
        fn = getattr(self._module, "test_dataloader", None)
        return self._sharded(fn()) if fn is not None else None

    def predict_dataloader(self):
        fn = getattr(self._module, "predict_dataloader", None)
        return self._sharded(fn()) if fn is not None else None


class Trainer:
    """Drive training through a :class:`TpuStrategy`.

    Args mirror the ``pl.Trainer`` subset the reference exercises in its
    tests (``tests/utils.py:213-233``): ``max_epochs``, ``max_steps``,
    ``callbacks``, ``limit_*_batches``, ``fast_dev_run``,
    ``resume_from_checkpoint``, plus ``strategy``/``plugins``.
    """

    def __init__(
        self,
        strategy=None,
        plugins=None,
        max_epochs: int = 1,
        max_steps: int = -1,
        callbacks: Optional[List[Callback]] = None,
        default_root_dir: str = "rlt_logs",
        seed: int = 0,
        precision: str = "f32",
        check_val_every_n_epoch: int = 1,
        limit_train_batches: int = -1,
        limit_val_batches: int = -1,
        log_every_n_steps: int = 50,
        accumulate_grad_batches: int = 1,
        megastep=None,
        update_sharding=None,
        grad_overlap_segments=None,
        enable_checkpointing: bool = True,
        fast_dev_run: bool = False,
        resume_from_checkpoint: Optional[str] = None,
        restart_dir: Optional[str] = None,
        restart_every_n_epochs: Optional[int] = None,
    ):
        # Imported here, not at module top: strategies imports the loop,
        # which lives beside this module (cycle otherwise).
        from ray_lightning_tpu.parallel.strategies import (
            LocalStrategy,
            TpuStrategy,
        )

        if strategy is None and plugins:
            # Reference-style: Trainer(plugins=[RayPlugin(...)])
            strategy = next(
                (p for p in plugins if isinstance(p, TpuStrategy)), None
            )
        if (restart_every_n_epochs is not None
                and restart_every_n_epochs < 1):
            raise ValueError("restart_every_n_epochs must be >= 1")
        self.strategy = strategy or LocalStrategy()
        self.callbacks: List[Callback] = list(callbacks or [])
        if enable_checkpointing and not any(
            isinstance(cb, ModelCheckpoint) for cb in self.callbacks
        ):
            self.callbacks.append(ModelCheckpoint(monitor=None))
        self.config = FitConfig(
            max_epochs=max_epochs,
            max_steps=max_steps,
            check_val_every_n_epoch=check_val_every_n_epoch,
            limit_train_batches=limit_train_batches,
            limit_val_batches=limit_val_batches,
            log_every_n_steps=log_every_n_steps,
            accumulate_grad_batches=accumulate_grad_batches,
            # Megastep execution mode (fuse K micro-steps into one
            # compiled scan — docs/PERFORMANCE.md "Host dispatch &
            # megastep").  None defers to the strategy's knob / the
            # RLT_MEGASTEP env bus / "auto".
            megastep=megastep,
            # Cross-replica sharded weight update (optimizer state +
            # update computation sharded over the batch axes on pure-DP
            # meshes — docs/PERFORMANCE.md).  None defers to the
            # strategy's knob / the RLT_UPDATE_SHARDING env bus /
            # "auto".
            update_sharding=update_sharding,
            # Backward-overlapped gradient sync (G trunk segments +
            # custom_vjp grad taps — docs/PERFORMANCE.md "Comm/compute
            # overlap").  None defers to the strategy's knob / the
            # RLT_GRAD_OVERLAP env bus / off.
            grad_overlap_segments=grad_overlap_segments,
            seed=seed,
            precision=precision,
            default_root_dir=default_root_dir,
            resume_from_checkpoint=resume_from_checkpoint,
            fast_dev_run=fast_dev_run,
            # Elastic-restart checkpoint location.  When None, strategies
            # with max_restarts > 0 manage a scratch dir themselves; a
            # caller-provided dir is written to (per-host sharded, see
            # utils/sharded_ckpt.py) and PRESERVED after the fit.
            restart_dir=restart_dir,
            restart_every_n_epochs=restart_every_n_epochs,
        )

        # Post-run artifacts (populated like reference post_dispatch).
        self.callback_metrics: Dict[str, float] = {}
        self.logged_metrics: Dict[str, float] = {}
        self.best_model_path: str = ""
        self.state = None  # host-side TrainState (numpy leaves) after fit
        self.predictions: Optional[np.ndarray] = None
        self.epochs_run: int = 0
        self.global_step: int = 0   # optimizer steps (Lightning convention)
        self.micro_step: int = 0    # micro-batches (= global_step unless
        # gradient accumulation is active)
        # Gradient-sync wire accounting from the workers (grad_sync_mode,
        # grad_sync_bytes, compression ratio — parallel/grad_sync.py).
        # Compatibility view: the same numbers appear as counters in the
        # unified ``telemetry_report`` below.
        self.comm_stats: Dict[str, Any] = {}
        # Fleet-wide telemetry (telemetry/aggregate.py): every worker's
        # snapshot merged into min/max/mean-across-ranks skew views.
        self.telemetry_report: Dict[str, Any] = {}
        # Live-monitor record (telemetry/monitor.py): heartbeat-derived
        # per-rank state, stall/straggler/crash events, flight-bundle
        # paths.  Populated after every monitored fit — the live
        # companion of ``telemetry_report``.
        self.monitor_report: Dict[str, Any] = {}
        self._monitor = None  # the RunMonitor of the fit in flight
        self._state_stream: Optional[bytes] = None

    # -- live stream routing (driver-side queue pump hook) ------------------
    def _attach_monitor(self, monitor) -> None:
        """Called by the strategy when a monitored fit starts."""
        self._monitor = monitor

    def _adopt_monitor(self, monitor) -> None:
        """Called by the strategy when the fit ends (either way)."""
        self.monitor_report = monitor.report()
        self._monitor = None

    def _on_stream_item(self, item: Any) -> None:
        """Route one worker→driver stream item by ``type``.

        ``heartbeat``/``event``/``log`` feed the RunMonitor; ``metrics``
        update ``callback_metrics`` — but ONLY from rank 0 (the same
        rank whose result package wins at post-dispatch).  Before this
        gate any worker could clobber driver metrics with a forged
        ``{"type": "metrics"}`` dict.
        """
        if not isinstance(item, dict):
            return
        if self._monitor is not None:
            self._monitor.on_item(item)
        if (
            item.get("type") == "metrics"
            and int(item.get("rank", 0)) == 0
        ):
            self.callback_metrics.update(item["metrics"])

    # -- stage entry points --------------------------------------------------
    def _resolve_datamodule(
        self, module: TpuModule, datamodule: Optional[TpuDataModule]
    ) -> TpuDataModule:
        if datamodule is not None:
            return datamodule
        if hasattr(module, "train_dataloader") or hasattr(
            module, "val_dataloader"
        ):
            return _ModuleDataModule(module)
        raise ValueError(
            "Provide a datamodule or implement *_dataloader on the module."
        )

    def fit(
        self,
        module: TpuModule,
        datamodule: Optional[TpuDataModule] = None,
    ) -> "Trainer":
        dm = self._resolve_datamodule(module, datamodule)
        # Fresh monitor record per fit: each elastic attempt's monitor is
        # seeded with the prior attempts' events by the strategy, so the
        # LAST adopted report (success or failure) narrates the whole
        # fit — but it must not inherit a previous fit's.
        self.monitor_report = {}
        self.strategy.setup(self)
        try:
            results = self.strategy.run(
                "fit", module, dm, self.config, self.callbacks, trainer=self
            )
        finally:
            self.strategy.teardown()
        self._post_dispatch_fit(results)
        return self

    def _post_dispatch_fit(self, results: List[Dict[str, Any]]) -> None:
        """Adopt rank-0's result package (≙ reference ``post_dispatch``,
        ``ray_ddp.py:362-401``)."""
        rank0 = next(r for r in results if r.get("rank") == 0)
        self._state_stream = rank0["state_stream"]
        self.state = load_state_stream(self._state_stream)
        self.callback_metrics.update(rank0["callback_metrics"])
        self.logged_metrics.update(rank0["logged_metrics"])
        self.best_model_path = rank0["best_model_path"]
        self.epochs_run = rank0["epochs_run"]
        self.global_step = rank0["global_step"]
        self.micro_step = rank0.get("micro_step", self.global_step)
        self.comm_stats = dict(rank0.get("comm_stats", {}))
        self._merge_telemetry(results, replace=True)
        # Driver-side callback objects reflect what happened remotely
        # (≙ best_model_path adoption, ray_ddp.py:393-395 — generalized).
        for cb, cb_state in zip(self.callbacks, rank0["callback_states"]):
            cb.load_state_dict(cb_state)

    def _merge_telemetry(self, results: List[Dict[str, Any]],
                         replace: bool = False) -> None:
        """Merge EVERY rank's telemetry snapshot (each result package
        carries one — the non-zero ranks' packages exist for exactly
        this) into the fleet skew report.  Runs for fit, eval AND
        predict.  A fit REPLACES the report (even with an empty one —
        telemetry="off" must read as off); eval/predict update it only
        when they actually produced one, so a quick validate never
        wipes the fit's record."""
        from ray_lightning_tpu.telemetry import merge_snapshots

        report = merge_snapshots([r.get("telemetry") for r in results])
        if report or replace:
            self.telemetry_report = report

    @property
    def params(self):
        """Trained parameters (host numpy pytree) after :meth:`fit`."""
        return None if self.state is None else self.state.params

    def _run_eval(
        self,
        kind: str,
        module: TpuModule,
        datamodule: Optional[TpuDataModule],
        ckpt_path: Optional[str],
    ) -> Dict[str, float]:
        dm = self._resolve_datamodule(module, datamodule)
        self.strategy.setup(self)
        try:
            results = self.strategy.run(
                kind,
                module,
                dm,
                self.config,
                self.callbacks,
                trainer=self,
                params_stream=self._params_stream_for_eval(ckpt_path),
                ckpt_path=ckpt_path,
            )
        finally:
            self.strategy.teardown()
        rank0 = next(r for r in results if r.get("rank") == 0)
        metrics = rank0["callback_metrics"]
        self.callback_metrics.update(metrics)
        self._merge_telemetry(results)
        return metrics

    def _params_stream_for_eval(self, ckpt_path: Optional[str]):
        if ckpt_path is not None:
            return None  # workers load from the checkpoint file directly
        return self._state_stream_params()

    def _state_stream_params(self) -> Optional[bytes]:
        if self.state is None:
            return None
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        return to_state_stream(self.state.params)

    def validate(
        self,
        module: TpuModule,
        datamodule: Optional[TpuDataModule] = None,
        ckpt_path: Optional[str] = None,
    ) -> Dict[str, float]:
        return self._run_eval("validation", module, datamodule, ckpt_path)

    def test(
        self,
        module: TpuModule,
        datamodule: Optional[TpuDataModule] = None,
        ckpt_path: Optional[str] = None,
    ) -> Dict[str, float]:
        return self._run_eval("test", module, datamodule, ckpt_path)

    def predict(
        self,
        module: TpuModule,
        datamodule: Optional[TpuDataModule] = None,
        ckpt_path: Optional[str] = None,
    ) -> np.ndarray:
        dm = self._resolve_datamodule(module, datamodule)
        self.strategy.setup(self)
        try:
            results = self.strategy.run(
                "predict",
                module,
                dm,
                self.config,
                [],
                trainer=self,
                params_stream=self._params_stream_for_eval(ckpt_path),
                ckpt_path=ckpt_path,
            )
        finally:
            self.strategy.teardown()
        self._merge_telemetry(results)
        # Reassemble dataset row order: every global batch was split
        # host-contiguously (NumpyLoader), so interleave ranks per batch —
        # batch b = [rank0's slice, rank1's slice, ...] — then chain
        # batches.  (Upgrade over the reference, which returned rank-0
        # results only.)
        ordered = sorted(results, key=lambda r: r["rank"])
        per_rank = [r["prediction_batches"] for r in ordered]
        counts = {len(b) for b in per_rank}
        if len(counts) > 1:
            # A rank with fewer batches would silently drop the other
            # ranks' tail predictions; make the data-sharding bug loud.
            raise ValueError(
                "Ragged per-rank prediction batch counts "
                f"{[len(b) for b in per_rank]}: every rank must see the "
                "same number of batches (check the datamodule's sharding "
                "/ drop_last handling)."
            )
        num_batches = counts.pop() if counts else 0
        batches = [
            np.concatenate([per_rank[rank][b] for rank in range(len(per_rank))])
            for b in range(num_batches)
        ]
        self.predictions = np.concatenate(batches)
        return self.predictions

    def save_checkpoint(self, path: str) -> None:
        """Persist the post-fit state as a topology-independent stream."""
        if self._state_stream is None:
            raise RuntimeError("No trained state; call fit() first.")
        payload_dir = os.path.dirname(path)
        if payload_dir:
            os.makedirs(payload_dir, exist_ok=True)
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
        )

        payload = {
            "state": self.state,
            "epoch": self.epochs_run - 1,
            "global_step": self.global_step,
            "callback_metrics": dict(self.callback_metrics),
        }
        state_stream_to_file(to_state_stream(payload), path)
