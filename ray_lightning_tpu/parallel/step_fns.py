"""Jitted step-function builders — the gradient-sync hot path.

≙ the reference's entire L2 collective layer: where torch DDP wraps the
model so backward triggers NCCL bucketed all-reduce (wrap at reference
``ray_ddp.py:483``, backend init ``ray_ddp.py:430-433``), here the
data-parallel mean **is part of the compiled program**:

* **GSPMD flavor** (``mode="gspmd"``, ≙ ``RayPlugin``/DDP): the batch is
  sharded over the ``data`` mesh axis, the loss is a mean over the global
  batch, and ``jax.grad`` of that mean *is* the all-reduced gradient — XLA
  inserts and schedules the collectives (overlapped with compute on ICI).
  ZeRO sharding arrives purely via in/out shardings on the train state.

* **shard_map flavor** (``mode="shard_map"``, ≙ ``HorovodRayPlugin``): the
  per-device program is explicit SPMD — each device computes grads on its
  shard and calls ``jax.lax.pmean`` (the ring-all-reduce analogue of
  ``hvd.allreduce``, reference ``ray_horovod.py:196``).  Numerically
  equivalent; exists as the second execution flavor and as the
  explicitly-scheduled escape hatch.

Both flavors donate the input state (buffers are reused in-place on HBM)
and return (new_state, metrics) with metrics mesh-global, so every host
logs identical values and callbacks (early stopping) agree without extra
broadcasts.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule, TrainState
from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit
from . import sharding as shardlib

__all__ = [
    "build_train_step",
    "make_multi_step",
    "build_eval_step",
    "build_predict_step",
]


def _refuse_sharded_state(shardings: Any, where: str) -> None:
    """shard_map flavors replicate params/state on every device; refuse
    non-trivial shardings loudly rather than silently resharding."""
    nontrivial = [
        sh.spec
        for sh in jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        if isinstance(sh, NamedSharding)
        and any(e is not None for e in sh.spec)
    ]
    if nontrivial:
        raise ValueError(
            f"mode='{where}' (HorovodRayStrategy flavor) replicates the "
            f"state and cannot honor shardings (e.g. {nontrivial[0]}); "
            "drop param_partition_specs / model-parallel mesh axes / "
            "zero_stage or use the gspmd flavor."
        )


def _loss_and_grads(module: TpuModule, params, batch, rng):
    def loss_fn(p):
        loss, logs = module.training_step(p, batch, rng)
        return loss, logs

    (loss, logs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    logs = dict(logs)
    logs.setdefault("loss", loss)
    return grads, logs


def _gspmd_raw_step(module: TpuModule, tx, grad_sync: Optional[Any]):
    """The unjitted gspmd step body — shared by the single-step jit and
    the megastep scan (both must train the SAME program or parity dies).
    """
    if grad_sync is not None:
        synced = grad_sync.build_synced_grad_fn()
        wire_bytes = float(grad_sync.bytes_per_step)

        def raw_step(state: TrainState, batch, rng):
            if grad_sync.use_ef:
                grads, logs, new_resid = synced(
                    state.params, state.grad_residual, batch, rng
                )
            else:
                grads, logs = synced(state.params, batch, rng)
                new_resid = state.grad_residual
            logs = dict(logs)
            # Wire accounting rides the step logs so the per-step
            # bytes-on-wire land in callback_metrics/bench artifacts.
            logs["grad_sync_bytes"] = jnp.float32(wire_bytes)
            new_state = state.apply_gradients(grads, tx)
            new_state = TrainState(
                new_state.params, new_state.opt_state, new_state.step,
                new_resid,
            )
            return new_state, logs
    else:
        def raw_step(state: TrainState, batch, rng):
            grads, logs = _loss_and_grads(
                module, state.params, batch, rng
            )
            new_state = state.apply_gradients(grads, tx)
            return new_state, logs

    return raw_step


def _single_device_raw_step(module: TpuModule, tx):
    def raw_step(state: TrainState, batch, rng):
        grads, logs = _loss_and_grads(module, state.params, batch, rng)
        return state.apply_gradients(grads, tx), logs

    return raw_step


def _shard_map_raw_step(
    module: TpuModule, tx, mesh: Mesh, zero_stage: int,
    state_shardings: Optional[Any],
):
    """The unjitted shard_map step (explicit per-device collectives) —
    shared by the single-step jit and the megastep scan."""
    from ray_lightning_tpu.utils.jax_compat import shard_map

    # The shard_map flavor replicates the train state on every device
    # (the Horovod duality: explicit per-device collectives, no state
    # sharding).  Combining it with ZeRO or TP-annotated modules would
    # silently reshard — refuse loudly instead (VERDICT weak #7).
    if zero_stage > 0:
        raise ValueError(
            "mode='shard_map' (HorovodRayStrategy) replicates the "
            f"train state and cannot honor zero_stage={zero_stage}; "
            "use the gspmd flavor (RayShardedStrategy) for ZeRO "
            "sharding."
        )
    _refuse_sharded_state(state_shardings, "shard_map")

    # Shard the batch over every batch-parallel axis the mesh actually
    # has (matching make_global_batch), not a hard-coded "data".
    batch_axes = shardlib.data_axes(mesh)
    if not batch_axes:
        raise ValueError(
            "shard_map mode needs a data/fsdp mesh axis to shard the "
            f"batch over; mesh axes = {mesh.axis_names}"
        )
    data_axis = batch_axes if len(batch_axes) > 1 else batch_axes[0]
    repl_spec = P()
    batch_spec = P(data_axis)

    def per_device_step(state: TrainState, batch, rng):
        # The explicit all-reduce of the Horovod duality: each device
        # differentiates its LOCAL mean loss, then pmean's the grads
        # across the data axis (hvd.allreduce ≙ collective over ICI).
        # check_vma=False makes this formulation version-stable: it
        # disables the automatic replicated-param cotangent psum (so
        # the explicit pmean never double-counts) and skips the
        # output-replication inference, which is satisfied by
        # construction — grads and logs are pmean'd, so every device
        # computes identical updates.
        def loss_fn(p):
            loss, logs = module.training_step(p, batch, rng)
            return loss, logs

        (loss, logs), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = jax.lax.pmean(grads, axis_name=data_axis)
        logs = dict(logs)
        logs.setdefault("loss", loss)
        logs = jax.lax.pmean(logs, axis_name=data_axis)
        new_state = state.apply_gradients(grads, tx)
        return new_state, logs

    return shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(repl_spec, batch_spec, repl_spec),
        out_specs=(repl_spec, repl_spec),
        check_vma=False,
    )


def build_train_step(
    module: TpuModule,
    tx,
    mesh: Optional[Mesh],
    mode: str = "gspmd",
    zero_stage: int = 0,
    state_shardings: Optional[Any] = None,
    grad_sync: Optional[Any] = None,
) -> Callable[[TrainState, Any, jax.Array], Tuple[TrainState, dict]]:
    """Compile one optimizer step over the mesh.

    Returns ``step(state, batch, rng) -> (new_state, metrics)``.
    ``batch`` must already be device-placed (global jax.Arrays sharded on
    the data axis for gspmd; see :func:`..sharding.make_global_batch`).

    ``grad_sync`` (a resolved :class:`..grad_sync.GradSync`, gspmd mode
    only) replaces the implicit full-width gradient all-reduce with the
    explicit bucketed/quantized pipeline: a shard_map island computes
    per-device partial grads and runs the compressed collectives, then
    the optimizer update continues under GSPMD (ZeRO-1 state sharding
    composes unchanged).  With error feedback the state must already
    carry its residual (``GradSync.attach_residual``).
    """
    if mesh is None:
        # Single-device path (driver-local smoke tests, ≙ non-distributed
        # Lightning fit).
        return ledgered_jit(
            _single_device_raw_step(module, tx), site="train/step",
            arg_names=("state", "batch", "rng"), donate_argnums=0,
        )

    if mode == "gspmd":
        repl = shardlib.replicated(mesh)
        if state_shardings is None:
            # A single sharding acts as a pytree prefix: replicate the
            # whole train state (plain DDP, zero_stage=0).
            state_shardings = repl
        batch_sh = shardlib.batch_sharding(mesh)
        raw_step = _gspmd_raw_step(module, tx, grad_sync)

        # in/out shardings: state keeps its (possibly ZeRO-sharded) layout,
        # batch arrives data-sharded, rng + metrics replicated.
        step = ledgered_jit(
            raw_step, site="train/step",
            arg_names=("state", "batch", "rng"),
            in_shardings=(state_shardings, batch_sh, repl),
            out_shardings=(state_shardings, repl),
            donate_argnums=0,
        )
        return step

    if mode == "shard_map":
        sharded = _shard_map_raw_step(
            module, tx, mesh, zero_stage, state_shardings
        )
        return ledgered_jit(
            sharded, site="train/step",
            arg_names=("state", "batch", "rng"), donate_argnums=0,
        )

    raise ValueError(f"Unknown step mode {mode!r} (expected gspmd|shard_map)")


def make_multi_step(
    module: TpuModule,
    tx,
    mesh: Optional[Mesh],
    k: int,
    mode: str = "gspmd",
    zero_stage: int = 0,
    state_shardings: Optional[Any] = None,
    grad_sync: Optional[Any] = None,
) -> Callable[[TrainState, Any, jax.Array, Any], Tuple[TrainState, dict]]:
    """Compile a **megastep**: ``k`` micro-steps fused into ONE program.

    ``multi(state, kbatch, base_rng, start) -> (new_state, aux)`` where
    ``kbatch`` is ``k`` pre-staged micro-batches stacked on a new leading
    axis (leaf shape ``(k, B, ...)``, sharded ``P(None, data)`` on a
    mesh — :func:`..sharding.make_global_stacked_batch`), ``base_rng`` is
    the fit's base PRNG key and ``start`` the micro-step index of the
    stride's first inner step (a traced int32 scalar — NOT static, so
    every stride reuses one executable).

    The inner step is the SAME raw step the single-step path jits
    (``_gspmd_raw_step`` / ``_shard_map_raw_step``), scanned with
    ``lax.scan``; the per-step RNG is ``fold_in(base_rng, start + i)``
    — exactly what the per-step loop computes on the host — so the
    trained trajectory is identical up to float association order.

    Metric bookkeeping stays ON DEVICE: ``aux`` carries, per log key,
    the finite-filtered f32 ``sum`` and finite ``cnt`` over the stride
    (the running-mean contract of ``_RunningMeanLogs``, summed over the
    stride axis only — non-scalar logs keep their shape) plus ``last``
    (the final inner step's logs, what the boundary logs/hooks see).
    The host touches ONE dispatch per ``k`` micro-batches and zero
    device syncs.
    """
    if k < 2:
        raise ValueError(f"make_multi_step needs k >= 2, got {k}")

    if mesh is None:
        raw_step = _single_device_raw_step(module, tx)
    elif mode == "gspmd":
        raw_step = _gspmd_raw_step(module, tx, grad_sync)
    elif mode == "shard_map":
        raw_step = _shard_map_raw_step(
            module, tx, mesh, zero_stage, state_shardings
        )
    else:
        raise ValueError(
            f"Unknown step mode {mode!r} (expected gspmd|shard_map)"
        )

    def multi(state: TrainState, kbatch, base_rng, start):
        idx = jnp.arange(k, dtype=jnp.int32)

        def body(carry, xs):
            batch_i, i = xs
            rng_i = jax.random.fold_in(base_rng, start + i)
            new_state, logs = raw_step(carry, batch_i, rng_i)
            return new_state, dict(logs)

        state, seq = jax.lax.scan(body, state, (kbatch, idx))
        # On-device metric accumulation over the stride axis (axis 0);
        # everything else keeps the log's own shape, mirroring the host
        # accumulator's elementwise running mean.
        sums, cnts, last = {}, {}, {}
        for key, stacked in seq.items():
            v32 = jnp.asarray(stacked).astype(jnp.float32)
            finite = jnp.isfinite(v32)
            sums[key] = jnp.sum(jnp.where(finite, v32, 0.0), axis=0)
            cnts[key] = jnp.sum(finite.astype(jnp.float32), axis=0)
            last[key] = stacked[-1]
        return state, {"sum": sums, "cnt": cnts, "last": last}

    megastep_names = ("state", "kbatch", "base_rng", "start")
    if mesh is None or mode == "shard_map":
        return ledgered_jit(
            multi, site=f"train/megastep_k{k}", arg_names=megastep_names,
            donate_argnums=0,
        )

    repl = shardlib.replicated(mesh)
    if state_shardings is None:
        state_shardings = repl
    kbatch_sh = shardlib.stacked_batch_sharding(mesh)
    return ledgered_jit(
        multi, site=f"train/megastep_k{k}", arg_names=megastep_names,
        in_shardings=(state_shardings, kbatch_sh, repl, repl),
        out_shardings=(state_shardings, repl),
        donate_argnums=0,
    )


def build_eval_step(
    module: TpuModule,
    mesh: Optional[Mesh],
    kind: str = "validation",
    mode: str = "gspmd",
    params_shardings: Optional[Any] = None,
) -> Callable[[Any, Any], dict]:
    """Compile one metric-producing eval step: ``(params, batch) -> logs``."""
    step_method = (
        module.validation_step if kind == "validation" else module.test_step
    )

    if mesh is None:
        return ledgered_jit(
            lambda params, batch: dict(step_method(params, batch)),
            site=f"eval/{kind}", arg_names=("params", "batch"),
        )

    if mode == "shard_map":
        from ray_lightning_tpu.utils.jax_compat import shard_map

        # Same refusal as the train step: shard_map replicates params, so
        # a ZeRO-3/TP-placed model would silently all-gather here.
        _refuse_sharded_state(params_shardings, "shard_map eval")

        batch_axes = shardlib.data_axes(mesh)
        if not batch_axes:
            raise ValueError(
                "shard_map mode needs a data/fsdp mesh axis to shard the "
                f"batch over; mesh axes = {mesh.axis_names}"
            )
        data_axis = batch_axes if len(batch_axes) > 1 else batch_axes[0]

        def per_device(params, batch):
            logs = dict(step_method(params, batch))
            return jax.lax.pmean(logs, axis_name=data_axis)

        return ledgered_jit(
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(data_axis)),
                out_specs=P(),
                # Outputs are pmean'd — replicated by construction; the
                # inference-based checker can't always prove it.
                check_vma=False,
            ),
            site=f"eval/{kind}", arg_names=("params", "batch"),
        )

    repl = shardlib.replicated(mesh)
    batch_sh = shardlib.batch_sharding(mesh)
    in_sh = (params_shardings if params_shardings is not None else repl,
             batch_sh)
    return ledgered_jit(
        lambda params, batch: dict(step_method(params, batch)),
        site=f"eval/{kind}", arg_names=("params", "batch"),
        in_shardings=in_sh,
        out_shardings=repl,
    )


def build_predict_step(
    module: TpuModule,
    mesh: Optional[Mesh],
    params_shardings: Optional[Any] = None,
):
    """Compile ``(params, batch) -> outputs`` with outputs batch-sharded.

    Outputs keep the data-axis sharding so each host can ``device_get``
    its own slice (addressable shards) for driver-side concatenation.
    """
    if mesh is None:
        return ledgered_jit(
            module.predict_step, site="eval/predict",
            arg_names=("params", "batch"),
        )
    repl = shardlib.replicated(mesh)
    batch_sh = shardlib.batch_sharding(mesh)
    return ledgered_jit(
        module.predict_step, site="eval/predict",
        arg_names=("params", "batch"),
        in_shardings=(params_shardings if params_shardings is not None
                      else repl, batch_sh),
        out_shardings=batch_sh,
    )
