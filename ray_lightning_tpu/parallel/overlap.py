"""Backward-overlapped gradient synchronization (the latency-hiding layer).

:mod:`ray_lightning_tpu.parallel.grad_sync` cut the DCN wire *width*
(block-scaled int8 + error feedback), but its collectives fire after
``jax.grad`` returns — the whole wire time is exposed, serialized behind
the backward.  This module moves the sync *into* the backward graph so
XLA's latency-hiding scheduler can overlap each group's collective with
the backward compute that is still pending:

* the module partitions its params into **groups ordered by backward
  completion** (``module.grad_overlap_groups``) — for a transformer LM
  the head / final-LN grads complete *first* (loss → layer N → … →
  layer 1 → embedding), so their bucket collectives can hide under the
  entire trunk backward;
* the trunk's layer scan is split into ``G`` sub-scans (knob
  ``grad_overlap_segments`` / ``RLT_GRAD_OVERLAP``) so each segment's
  stacked grads emerge at a segment boundary instead of all at once;
* every group is wrapped in a ``jax.custom_vjp`` **grad tap**
  (:class:`TapPlane`): the forward is the identity, the backward
  receives the group's complete local cotangent — the tap replaces all
  uses of the subtree, so by VJP accounting the accumulated cotangent
  *is* the group's full local grad — and runs the group's bucketed
  quantized all-reduce right there, mid-backward.

Error-feedback residuals thread through the same taps: each group owns a
contiguous slice of the per-device residual row, passed in as a tap
operand (its VJP — a ``dynamic_slice`` — scatters the group's new
residual back into the row cotangent, so the summed cotangent of the
full row is the reassembled next-step residual).  The group layout is an
:class:`OverlapPlan`, which exposes the same accounting interface as a
step-end :class:`~ray_lightning_tpu.parallel.grad_sync.BucketPlan` —
wire bytes are identical by construction (same codec, same alignment
rule), so ``grad_sync_bytes`` and the EF resume path
(``reconcile_resumed_state``) carry over unchanged.

``grad_overlap_segments`` unset/""/0 resolves to the step-end path —
the zero-risk default until a hardware window confirms the win.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "resolve_grad_overlap",
    "normalize_grad_overlap",
    "GroupPlan",
    "OverlapPlan",
    "build_overlap_plan",
    "TapPlane",
]


def normalize_grad_overlap(value: Any) -> Optional[int]:
    """Validate a ``grad_overlap_segments`` knob value and return its
    normal form: None (defer to the env bus) or an int >= 0 (0 = off;
    "off"/"" are accepted as 0, numeric strings become ints)."""
    if value is None:
        return None
    if isinstance(value, str):
        s = value.strip().lower()
        if s in ("", "off", "none"):
            return 0
        try:
            value = int(s)
        except ValueError:
            raise ValueError(
                f"grad_overlap_segments={value!r}: expected 'off', '' or "
                "an integer G >= 0"
            ) from None
    if isinstance(value, bool) or not isinstance(value, int):
        raise TypeError(
            f"grad_overlap_segments must be None, 'off' or an int >= 0; "
            f"got {type(value).__name__}"
        )
    if value < 0:
        raise ValueError(
            f"grad_overlap_segments must be >= 0, got {value}"
        )
    return value


def resolve_grad_overlap(value: Any) -> int:
    """The concrete trunk-segment count G for this fit (0 = step-end).

    Strongest first: an explicit ``grad_overlap_segments=`` on the
    Trainer/strategy → the ``RLT_GRAD_OVERLAP`` env bus (forwarded to
    workers like ``RLT_GRAD_COMM``) → off.  An empty ``RLT_GRAD_OVERLAP=``
    means "off" (the operator cleared the knob), same as every other
    normalization path.
    """
    value = normalize_grad_overlap(value)
    if value is None:
        value = normalize_grad_overlap(os.environ.get("RLT_GRAD_OVERLAP"))
    return 0 if value is None else int(value)


@dataclasses.dataclass(frozen=True)
class GroupPlan:
    """One tap group: a param subtree synced at its backward boundary."""

    name: str
    #: Tapped at loss entry (a sub-dict of TOP-LEVEL param keys, applied
    #: by dict replacement so every read — including a tied LM head —
    #: sees the tapped value) vs inside the module's own forward.
    entry: bool
    keys: Tuple[str, ...]          # top-level param keys (entry groups)
    plan: Any                      # group-local grad_sync.BucketPlan
    resid_offset: int              # group's start in the residual row
    leaf_sizes: Tuple[int, ...]    # tree-order element counts (validation)


@dataclasses.dataclass(frozen=True)
class OverlapPlan:
    """Segment-aware bucket layout, backward-completion ordered.

    Duck-types :class:`~ray_lightning_tpu.parallel.grad_sync.BucketPlan`'s
    accounting interface (``wire_bytes_per_step`` / ``collectives_per_step``
    / ``total_padded`` / …) so an active :class:`GradSync` can carry it as
    its ``plan`` — stats, residual init and checkpoint reconciliation work
    unchanged.
    """

    groups: Tuple[GroupPlan, ...]
    trunk_segments: int            # G sub-scans the module's forward runs
    n_shards: int
    block_size: int
    total_elems: int
    total_padded: int
    full_width_bytes: int

    @property
    def num_buckets(self) -> int:
        return sum(g.plan.num_buckets for g in self.groups)

    def wire_bytes_per_step(self, mode: str) -> int:
        return sum(g.plan.wire_bytes_per_step(mode) for g in self.groups)

    def collectives_per_step(self, mode: str) -> int:
        return sum(g.plan.collectives_per_step(mode) for g in self.groups)

    def group(self, name: str) -> GroupPlan:
        for g in self.groups:
            if g.name == name:
                return g
        raise KeyError(name)


def _leaf_sizes(subtree: Any) -> Tuple[int, ...]:
    sizes = []
    for leaf in jax.tree_util.tree_leaves(subtree):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        sizes.append(int(np.prod(shape)) if shape else 1)
    return tuple(sizes)


def build_overlap_plan(
    group_specs: Sequence[Tuple[str, Any, bool]],
    n_shards: int,
    bucket_bytes: int = 4 * 2**20,
    block_size: int = 256,
) -> OverlapPlan:
    """Build per-group bucket plans from a module's
    ``grad_overlap_groups`` spec: an ordered (backward-completion-first)
    sequence of ``(name, abstract_subtree, entry)``.

    Each group is bucketed independently with the step-end packer
    (``grad_sync.build_bucket_plan``) — same codec, same
    ``n_shards * block_size`` alignment — and owns a contiguous slice of
    the per-device EF residual row at ``resid_offset``.  Group
    granularity costs at most ``align - 1`` extra pad elements per group
    versus one monolithic plan.
    """
    from ray_lightning_tpu.parallel.grad_sync import build_bucket_plan

    groups: List[GroupPlan] = []
    offset = 0
    total_elems = 0
    full_width_bytes = 0
    trunk_segments = 0
    seen: set = set()
    for name, subtree, entry in group_specs:
        if name in seen:
            raise ValueError(f"duplicate grad-overlap group name {name!r}")
        seen.add(name)
        plan = build_bucket_plan(subtree, n_shards, bucket_bytes, block_size)
        keys: Tuple[str, ...] = ()
        if entry:
            if not isinstance(subtree, dict):
                raise ValueError(
                    f"entry grad-overlap group {name!r} must be a dict of "
                    "top-level param keys (applied by dict replacement); "
                    f"got {type(subtree).__name__}"
                )
            keys = tuple(subtree.keys())
        else:
            trunk_segments += 1
        groups.append(
            GroupPlan(
                name=name,
                entry=entry,
                keys=keys,
                plan=plan,
                resid_offset=offset,
                leaf_sizes=_leaf_sizes(subtree),
            )
        )
        offset += plan.total_padded
        total_elems += plan.total_elems
        full_width_bytes += plan.full_width_bytes
    if not groups:
        raise ValueError("grad_overlap_groups produced no groups")
    return OverlapPlan(
        groups=tuple(groups),
        trunk_segments=trunk_segments,
        n_shards=n_shards,
        block_size=block_size,
        total_elems=total_elems,
        total_padded=offset,
        full_width_bytes=full_width_bytes,
    )


def _make_group_tap(grp: GroupPlan, axes, n_shards: int, block_size: int,
                    use_ef: bool):
    """The ``custom_vjp`` identity whose backward syncs the group.

    Primal: ``tap(leaves[, resid_slice]) -> leaves`` (tuple in, tuple
    out).  Backward: the incoming cotangent tuple is the group's
    complete per-device local grad (the tap replaces every use of the
    subtree), so the group's bucketed quantized all-reduce runs right
    here — mid-backward, with later-completing groups' compute still
    pending for XLA to overlap against.  The EF variant returns the
    group's fresh residual as the ``resid_slice`` cotangent; the
    enclosing ``dynamic_slice`` VJP scatters it back into the row.
    """
    from ray_lightning_tpu.parallel import grad_sync as gsync

    buckets = grp.plan.buckets

    if use_ef:
        @jax.custom_vjp
        def tap(leaves, resid_slice):
            del resid_slice
            return leaves

        def fwd(leaves, resid_slice):
            return leaves, resid_slice

        def bwd(resid_slice, ct):
            out, new_resid = gsync.sync_leaf_buckets(
                list(ct), buckets, resid_slice, axes, n_shards,
                block_size, use_ef=True,
            )
            if new_resid is None:  # bucketless group (all-empty leaves)
                new_resid = jnp.zeros_like(resid_slice)
            return tuple(out), new_resid

        tap.defvjp(fwd, bwd)
        return tap

    @jax.custom_vjp
    def tap(leaves):
        return leaves

    def fwd(leaves):
        return leaves, None

    def bwd(_, ct):
        out, _resid = gsync.sync_leaf_buckets(
            list(ct), buckets, None, axes, n_shards, block_size,
            use_ef=False,
        )
        return (tuple(out),)

    tap.defvjp(fwd, bwd)
    return tap


class TapPlane:
    """Trace-scoped tap registry for one differentiation of the loss.

    Built inside the grad-sync island's local loss and installed on the
    module's trainer context as ``grad_tap_plane`` for the duration of
    the traced ``training_step``, so module forwards can route param
    subtrees through :meth:`tap`.  Entry groups (top-level param keys —
    the LM head / embeddings) are applied here by dict replacement
    (:meth:`apply_entry_taps`) so *every* read of those params — the
    tied-softmax head included — sees the tapped value; trunk segment
    groups are tapped by the module at each sub-scan boundary.

    One plane serves exactly one trace: :meth:`check_consumed` raises if
    the forward skipped (or double-tapped) a group — a silent miss would
    quietly drop that group's gradient sync.
    """

    def __init__(self, oplan: OverlapPlan, axes, n_shards: int,
                 use_ef: bool, resid_row=None):
        self._oplan = oplan
        self._groups = {g.name: g for g in oplan.groups}
        self._axes = axes
        self._n = n_shards
        self._use_ef = use_ef
        self._resid_row = resid_row
        self.consumed: set = set()

    @property
    def trunk_segments(self) -> int:
        return self._oplan.trunk_segments

    def apply_entry_taps(self, params: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(params)
        for grp in self._oplan.groups:
            if not grp.entry:
                continue
            sub = {k: out[k] for k in grp.keys}
            out.update(self.tap(grp.name, sub))
        return out

    def tap(self, name: str, subtree: Any) -> Any:
        grp = self._groups.get(name)
        if grp is None:
            raise ValueError(
                f"grad tap {name!r} is not in the overlap plan "
                f"(groups: {sorted(self._groups)})"
            )
        if name in self.consumed:
            raise ValueError(
                f"grad tap {name!r} consumed twice in one trace — each "
                "group must be tapped exactly once per differentiation"
            )
        leaves, treedef = jax.tree_util.tree_flatten(subtree)
        sizes = _leaf_sizes(subtree)
        if sizes != grp.leaf_sizes:
            raise ValueError(
                f"grad tap {name!r}: subtree leaf layout {sizes} does "
                f"not match the plan's {grp.leaf_sizes} — the forward "
                "must tap the same subtree grad_overlap_groups declared"
            )
        self.consumed.add(name)
        fn = _make_group_tap(
            grp, self._axes, self._n, self._oplan.block_size, self._use_ef
        )
        if self._use_ef:
            resid_slice = jax.lax.dynamic_slice(
                self._resid_row, (grp.resid_offset,),
                (grp.plan.total_padded,),
            )
            out_leaves = fn(tuple(leaves), resid_slice)
        else:
            out_leaves = fn(tuple(leaves))
        return jax.tree_util.tree_unflatten(treedef, list(out_leaves))

    def check_consumed(self) -> None:
        missing = [
            g.name for g in self._oplan.groups
            if g.name not in self.consumed
        ]
        if missing:
            raise ValueError(
                f"grad overlap groups never tapped this trace: {missing} "
                "— the module's forward must route every declared "
                "subtree through trainer.grad_tap_plane.tap()"
            )
