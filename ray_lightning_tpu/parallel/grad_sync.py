"""Quantized, bucketed gradient synchronization (the DCN bandwidth layer).

The GSPMD train step syncs gradients implicitly: the loss is a mean over
the global batch, so ``jax.grad`` of it IS the all-reduced gradient — one
compiler-scheduled full-width collective.  On cross-host (DCN) meshes that
wire is the scale-out bound.  This module makes the sync explicit and
compressible:

* **bucketing** — the grad pytree is flattened in layer order and packed
  into size-bounded buckets (~4 MB default), so the sync is several
  independent collectives XLA may overlap with unrelated compute instead
  of one barrier-sized transfer;
* **block-scaled int8 wire** (``mode="int8"``) — each bucket is quantized
  per-block (:mod:`ray_lightning_tpu.ops.collective_quant`) before the
  two-phase compressed all-reduce: ~3.9× fewer bytes on the wire than
  f32 full-width at a bounded per-step rounding error;
* **error feedback** (``mode="int8_ef"``) — every device carries its own
  f32 compression-error residual in the train state
  (``TrainState.grad_residual``, sharded one row per device) and re-adds
  it to the next step's partial before quantizing, so the error
  telescopes instead of accumulating (1-bit-Adam/EF-SGD discipline);
* **wire accounting** — the analytic bytes-on-wire of the chosen mode
  (and of the full-width counterfactual) are recorded per step in the
  loop metrics (``grad_sync_bytes``) and in the fit result package, so a
  claimed traffic cut is an artifact, not a slide.

Mechanically the sync is a ``shard_map`` island inside the jitted step
(the same jit → shard_map pattern as the CE island): per-device partial
grads of the *local* loss (``check_vma=False`` keeps the replicated-param
cotangent un-psummed), quantized collectives over the batch axes, then
the optimizer update continues under GSPMD — ZeRO-1 optimizer-state
sharding composes unchanged.  Activation requires a batch-parallel-only
mesh and replicated params (``zero_stage <= 1``); anything else falls
back to full-width with a warning (quantized ZeRO-3 all-gather is the
named follow-on).  ``dcn_only=True`` (default) additionally keeps
single-host (ICI-only) meshes full-width — ICI is not the bottleneck the
compression pays for.  Env bus: ``RLT_GRAD_COMM``, ``RLT_GRAD_BUCKET_MB``,
``RLT_GRAD_BLOCK``, ``RLT_GRAD_DCN_ONLY``.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.ops import collective_quant as cq
from ray_lightning_tpu.utils.jax_compat import shard_map

from . import sharding as shardlib

__all__ = [
    "GradCommConfig",
    "Bucket",
    "BucketPlan",
    "build_bucket_plan",
    "sync_leaf_buckets",
    "GradSync",
    "maybe_build_grad_sync",
]

_MODES = ("full", "int8", "int8_ef")


@dataclasses.dataclass(frozen=True)
class GradCommConfig:
    """User-facing gradient-communication knobs.

    ``mode``: ``"full"`` (implicit XLA sync, the default), ``"int8"``
    (block-scaled quantized wire), ``"int8_ef"`` (int8 + error-feedback
    residual).  ``bucket_bytes`` bounds a bucket by its *full-width* f32
    footprint; ``block_size`` is the quantization granularity (elements
    per scale); ``dcn_only`` keeps single-process (ICI-only) meshes at
    full width even when an int8 mode is requested.
    """

    mode: str = "full"
    bucket_bytes: int = 4 * 2**20
    block_size: int = 256
    dcn_only: bool = True

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(
                f"grad_comm mode {self.mode!r}: expected one of {_MODES}"
            )
        if self.bucket_bytes < 4:
            raise ValueError("bucket_bytes must be >= 4 (one f32)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @classmethod
    def coerce(cls, value: Any) -> "GradCommConfig":
        """None | str | dict | GradCommConfig → GradCommConfig.

        ``None`` reads the ``RLT_GRAD_COMM`` env bus (workers inherit the
        driver's env exactly like ``RLT_COMPILE_CACHE``); absent that, the
        default is full-width — compression is always opt-in.
        """
        if isinstance(value, cls):
            return value
        if value is None:
            value = os.environ.get("RLT_GRAD_COMM") or "full"
        if isinstance(value, str):
            kw: dict = {"mode": value}
        elif isinstance(value, dict):
            kw = dict(value)
            if "mode" not in kw:
                # A dict without a mode (tuning knobs alone, or empty)
                # would silently coerce to full-width — the user clearly
                # expected to choose compression.  Pass a mode string or
                # None for the env-bus default instead.
                raise ValueError(
                    "grad_comm dict must name a 'mode' "
                    f"(one of {_MODES}); got keys {sorted(kw)}"
                )
        else:
            raise TypeError(
                f"grad_comm must be a mode string, dict or GradCommConfig; "
                f"got {type(value).__name__}"
            )
        env_mb = os.environ.get("RLT_GRAD_BUCKET_MB")
        if env_mb and "bucket_bytes" not in kw:
            kw["bucket_bytes"] = int(float(env_mb) * 2**20)
        env_block = os.environ.get("RLT_GRAD_BLOCK")
        if env_block and "block_size" not in kw:
            kw["block_size"] = int(env_block)
        env_dcn = os.environ.get("RLT_GRAD_DCN_ONLY")
        if env_dcn is not None and "dcn_only" not in kw:
            kw["dcn_only"] = env_dcn not in ("0", "false", "False", "")
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One sync unit: a contiguous (layer-order) run of grad leaves."""

    indices: Tuple[int, ...]   # flat-leaf positions
    sizes: Tuple[int, ...]     # elements per leaf
    size: int                  # total payload elements
    padded: int                # padded to n_shards * block_size
    offset: int                # start within the flat residual vector


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    buckets: Tuple[Bucket, ...]
    n_shards: int
    block_size: int
    total_elems: int           # un-padded payload elements
    total_padded: int          # residual vector length
    full_width_bytes: int      # f32 footprint of the whole grad pytree

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def wire_bytes_per_step(self, mode: str) -> int:
        """Analytic bytes each device puts on the wire per optimizer
        step.  Ring accounting — ``2(n-1)/n`` traversals of the payload
        (reduce-scatter + all-gather) for both the compressed path and
        the full-width counterfactual, so the ratio isolates the wire
        *width*, not the algorithm."""
        n = self.n_shards
        if n <= 1:
            return 0
        ring = 2.0 * (n - 1) / n
        if mode == "full":
            return int(ring * self.full_width_bytes)
        payload = sum(b.padded for b in self.buckets)          # int8 bytes
        scales = sum(b.padded // self.block_size for b in self.buckets) * 4
        return int(ring * (payload + scales))

    def collectives_per_step(self, mode: str) -> int:
        if mode == "full":
            return max(self.num_buckets, 1)  # XLA's implicit all-reduce(s)
        return 4 * self.num_buckets  # (all_to_all + all_gather) × (q, s)


def build_bucket_plan(
    abstract_grads: Any,
    n_shards: int,
    bucket_bytes: int = 4 * 2**20,
    block_size: int = 256,
) -> BucketPlan:
    """Pack the grad pytree's leaves, in tree (layer) order, into buckets
    bounded by ``bucket_bytes`` of full-width f32 footprint.

    A single leaf larger than the bound gets its own bucket (never
    split); the ragged tail bucket keeps whatever is left.  Each bucket
    is padded up to a multiple of ``n_shards * block_size`` so collective
    chunks align with quantization blocks (zero padding quantizes
    exactly, so it never pollutes the reduction).
    """
    leaves = jax.tree_util.tree_leaves(abstract_grads)
    align = n_shards * block_size
    max_elems = max(bucket_bytes // 4, 1)

    buckets: List[Bucket] = []
    cur_idx: List[int] = []
    cur_sizes: List[int] = []
    cur_total = 0
    offset = 0
    full_width_bytes = 0

    def flush():
        nonlocal cur_idx, cur_sizes, cur_total, offset
        if not cur_idx:
            return
        padded = -(-cur_total // align) * align
        buckets.append(
            Bucket(
                indices=tuple(cur_idx),
                sizes=tuple(cur_sizes),
                size=cur_total,
                padded=padded,
                offset=offset,
            )
        )
        offset += padded
        cur_idx, cur_sizes, cur_total = [], [], 0

    for i, leaf in enumerate(leaves):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        # Scalars are one element; a genuinely EMPTY leaf (a dim of 0 —
        # e.g. a placeholder param) has nothing to sync and must be
        # skipped, not counted as 1: a phantom element would desync the
        # bucket's padding from its actual payload.
        size = int(np.prod(shape)) if shape else 1
        if size == 0:
            continue
        full_width_bytes += size * 4
        if cur_total and cur_total + size > max_elems:
            flush()
        cur_idx.append(i)
        cur_sizes.append(size)
        cur_total += size
        if cur_total >= max_elems:
            flush()
    flush()

    return BucketPlan(
        buckets=tuple(buckets),
        n_shards=n_shards,
        block_size=block_size,
        total_elems=sum(b.size for b in buckets),
        total_padded=offset,
        full_width_bytes=full_width_bytes,
    )


def sync_leaf_buckets(
    leaves: List[Any],
    buckets: Sequence[Bucket],
    resid_vec,
    axes: Tuple[str, ...],
    n_shards: int,
    block_size: int,
    use_ef: bool,
) -> Tuple[List[Any], Optional[Any]]:
    """Per-device bucketed quantized all-reduce of flat grad leaves.

    The one sync kernel both paths share: the step-end
    :meth:`GradSync.build_synced_grad_fn` island runs it over the whole
    grad tree after ``jax.grad`` returns; the backward-overlapped grad
    taps (:mod:`ray_lightning_tpu.parallel.overlap`) run it per group on
    the cotangent, mid-backward.  ``leaves`` are this bucket set's grad
    leaves in plan order; ``resid_vec`` is the bucket set's contiguous
    EF-residual slice (bucket offsets are local to it).  Returns the
    synced leaves (original dtypes) and the concatenated new residual
    (``None`` without EF).  Must run inside ``shard_map`` over ``axes``.
    """
    out_leaves = list(leaves)
    resid_parts = []
    for b in buckets:
        parts = [
            leaves[i].reshape(-1).astype(jnp.float32)
            for i in b.indices
        ]
        flat = (
            jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        )
        if b.padded > b.size:
            flat = jnp.pad(flat, (0, b.padded - b.size))
        if use_ef:
            flat = flat + jax.lax.dynamic_slice(
                resid_vec, (b.offset,), (b.padded,)
            )
        reduced, err = cq.int8_all_reduce(
            flat, axes, n_shards, block_size, want_error=use_ef
        )
        if use_ef:
            resid_parts.append(err)
        pos = 0
        for i, sz in zip(b.indices, b.sizes):
            out_leaves[i] = (
                jax.lax.dynamic_slice(reduced, (pos,), (sz,))
                .reshape(leaves[i].shape)
                .astype(leaves[i].dtype)
            )
            pos += sz
    new_resid = (
        jnp.concatenate(resid_parts)
        if len(resid_parts) > 1
        else (resid_parts[0] if resid_parts else None)
    )
    return out_leaves, new_resid


class GradSync:
    """A resolved, active quantized-sync pipeline for one (module, mesh).

    Built by :func:`maybe_build_grad_sync`; consumed by
    ``step_fns.build_train_step`` (the island) and ``core.loop.run_fit``
    (residual attachment + comm stats).
    """

    def __init__(
        self,
        module: Any,
        mesh,
        cfg: GradCommConfig,
        axes: Tuple[str, ...],
        n_shards: int,
        plan: BucketPlan,
        overlap: Any = None,
    ):
        self.module = module
        self.mesh = mesh
        self.cfg = cfg
        self.axes = axes
        self.n_shards = n_shards
        # Backward-overlapped sync (parallel/overlap.py OverlapPlan):
        # when set, it duck-types BucketPlan's accounting/residual
        # interface and BECOMES the active plan — stats, residual init
        # and checkpoint reconciliation see one layout either way.
        self.overlap = overlap
        self.plan = overlap if overlap is not None else plan
        self.use_ef = cfg.mode == "int8_ef"

    # -- accounting ---------------------------------------------------------
    @property
    def bytes_per_step(self) -> int:
        return self.plan.wire_bytes_per_step(self.cfg.mode)

    def stats(self) -> dict:
        full = self.plan.wire_bytes_per_step("full")
        mine = self.bytes_per_step
        return {
            "grad_sync_mode": self.cfg.mode,
            "grad_sync_bytes": mine,
            "grad_sync_bytes_full_width": full,
            "grad_sync_compression_ratio": (
                round(full / mine, 3) if mine else None
            ),
            "grad_sync_buckets": self.plan.num_buckets,
            "grad_sync_collectives": self.plan.collectives_per_step(
                self.cfg.mode
            ),
            "grad_sync_block_size": self.plan.block_size,
            "grad_sync_devices": self.n_shards,
            # 0 = step-end sync; G >= 1 = backward-overlapped taps over
            # G trunk segments (parallel/overlap.py).
            "grad_sync_overlap_segments": (
                self.overlap.trunk_segments
                if self.overlap is not None else 0
            ),
        }

    def register_telemetry(self, telemetry) -> None:
        """Publish the wire accounting through the unified telemetry
        counters (numbers) / meta (mode strings) instead of a bespoke
        stats dict: ``grad_sync_bytes`` then appears in the fleet report
        (``trainer.telemetry_report``) next to step timings, and a
        grad-sync metadata span marks the plan in exported traces."""
        self._telemetry = telemetry
        for key, value in self.stats().items():
            if isinstance(value, bool) or value is None:
                telemetry.set_meta(key, value)
            elif isinstance(value, (int, float)):
                telemetry.set_counter(key, value)
            else:
                telemetry.set_meta(key, value)
        telemetry.tracer.instant(
            "grad_sync",
            mode=self.cfg.mode,
            buckets=self.plan.num_buckets,
            bytes_per_step=self.bytes_per_step,
        )

    # -- error-feedback residual -------------------------------------------
    def residual_sharding(self) -> NamedSharding:
        """One f32 row per sync participant, row ``d`` living on device
        ``d`` — per-device state expressed as a global array."""
        return NamedSharding(self.mesh, P(self.axes))

    def init_residual(self) -> jax.Array:
        zeros = jnp.zeros(
            (self.n_shards, self.plan.total_padded), jnp.float32
        )
        return jax.device_put(zeros, self.residual_sharding())

    def attach_residual(self, state, state_shardings):
        """Return (state, shardings) carrying the EF residual (no-ops for
        plain int8).  Must run before ``build_train_step`` so the jit's
        in/out sharding trees stay congruent with the state."""
        from ray_lightning_tpu.core.module import TrainState

        if not self.use_ef:
            return state, state_shardings
        new_state = TrainState(
            state.params, state.opt_state, state.step, self.init_residual()
        )
        if state_shardings is None:
            return new_state, None
        new_sh = TrainState(
            state_shardings.params,
            state_shardings.opt_state,
            state_shardings.step,
            self.residual_sharding(),
        )
        return new_state, new_sh

    def reconcile_resumed_state(self, host_state):
        """Normalize a resumed checkpoint against THIS run's residual
        layout: a stream written without EF (or from a different world
        size) gets a fresh zero residual — dropping at most one step of
        compression error; a stream written with EF resuming into a
        full/int8 run sheds it.

        The EF residual is **per-device** state (one row per sync
        participant): restored under a changed device count its rows no
        longer correspond to this run's devices, so a shape-mismatched
        residual is VALIDATED here and dropped — loudly (warning +
        ``grad_residual_dropped`` telemetry counter), never silently
        misapplied as another device's error history.
        """
        from ray_lightning_tpu.core.module import TrainState

        if not isinstance(host_state, TrainState):
            return host_state
        resid = getattr(host_state, "grad_residual", None)
        if not self.use_ef:
            if resid is None:
                return host_state
            return TrainState(
                host_state.params, host_state.opt_state, host_state.step
            )
        want = (self.n_shards, self.plan.total_padded)
        got = tuple(getattr(resid, "shape", ()))
        if resid is not None and got == want:
            return host_state
        if resid is not None:
            warnings.warn(
                f"checkpoint error-feedback residual has shape {got} "
                f"but this run syncs over {self.n_shards} devices "
                f"(want {want}) — the per-device residual does not "
                "survive an elastic world-size change; resetting to "
                "zero (at most one step of compression error is lost)"
            )
            tel = getattr(self, "_telemetry", None)
            if tel is not None:
                tel.add_counter("grad_residual_dropped", 1)
        return TrainState(
            host_state.params,
            host_state.opt_state,
            host_state.step,
            np.zeros(want, np.float32),
        )

    # -- the island ---------------------------------------------------------
    def build_synced_grad_fn(self):
        """The jit-traceable sync pipeline.

        EF: ``(params, residual, batch, rng) -> (grads, logs, residual')``;
        otherwise ``(params, batch, rng) -> (grads, logs)``.  ``grads`` are
        the dequantized world sum of per-device partials of the global
        mean loss — the same quantity the implicit full-width path feeds
        the optimizer.
        """
        if self.overlap is not None:
            return self._build_overlapped_fn()
        module = self.module
        axes = self.axes
        n = self.n_shards
        plan = self.plan
        block = plan.block_size
        use_ef = self.use_ef

        def _sync_buckets(grads, resid_row):
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            out_leaves, new_resid = sync_leaf_buckets(
                leaves, plan.buckets, resid_row, axes, n, block,
                use_ef=use_ef,
            )
            return jax.tree_util.tree_unflatten(treedef, out_leaves), new_resid

        def _local_grads(params, batch, rng):
            def local_loss(p):
                loss, logs = module.training_step(p, batch, rng)
                logs = dict(logs)
                logs.setdefault("loss", loss)
                # Scale so the world SUM of partials equals the gradient
                # of the global-mean loss (equal shard sizes are enforced
                # by make_global_batch's divisibility check).
                return loss / n, logs

            (_, logs), grads = jax.value_and_grad(
                local_loss, has_aux=True
            )(params)
            # Per-shard log values (local means) → mesh-global means, so
            # every host logs identical values, same as the gspmd flavor.
            logs = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axes), logs
            )
            return grads, logs

        batch_spec = P(axes)
        if use_ef:
            def island(params, residual, batch, rng):
                grads, logs = _local_grads(params, batch, rng)
                grads, new_resid = _sync_buckets(grads, residual[0])
                return grads, logs, new_resid[None]

            return shard_map(
                island,
                mesh=self.mesh,
                in_specs=(P(), P(axes), batch_spec, P()),
                out_specs=(P(), P(), P(axes)),
                check_vma=False,
            )

        def island(params, batch, rng):
            grads, logs = _local_grads(params, batch, rng)
            grads, _ = _sync_buckets(grads, None)
            return grads, logs

        return shard_map(
            island,
            mesh=self.mesh,
            in_specs=(P(), batch_spec, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )

    def _build_overlapped_fn(self):
        """The backward-overlapped sync pipeline — same signature
        contract as the step-end island, but the sync is *part of the
        differentiation*: every param group is wrapped in a custom_vjp
        grad tap (parallel/overlap.py) whose backward runs the group's
        bucketed quantized all-reduce the moment its cotangent
        completes, so XLA can overlap it with the backward compute
        still pending for earlier-completing layers.

        EF residuals ride the cotangent: the residual row is a second
        differentiated argument — each tap consumes its group's slice
        and returns the group's fresh residual as that slice's
        cotangent, so ``d(loss)/d(residual_row)`` *is* the reassembled
        next-step residual (the slices are disjoint, so the VJP's
        scatter-add reassembles exactly).  No post-grad write-back pass,
        and the result is bitwise the same residual layout the step-end
        path checkpoints.
        """
        from ray_lightning_tpu.parallel.overlap import TapPlane

        module = self.module
        axes = self.axes
        n = self.n_shards
        oplan = self.overlap
        use_ef = self.use_ef

        def _pmean_logs(logs):
            return jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axes), logs
            )

        def _tapped_loss(params, resid_row, batch, rng):
            plane = TapPlane(oplan, axes, n, use_ef, resid_row=resid_row)
            params = plane.apply_entry_taps(params)
            # The module's forward picks the plane up from its trainer
            # context to tap each trunk segment at its sub-scan
            # boundary; cleared in ``finally`` so eval/predict traces
            # never see a stale plane.
            trainer = getattr(module, "trainer", None)
            if trainer is not None:
                trainer.grad_tap_plane = plane
            try:
                loss, logs = module.training_step(params, batch, rng)
            finally:
                if trainer is not None:
                    trainer.grad_tap_plane = None
            plane.check_consumed()
            logs = dict(logs)
            logs.setdefault("loss", loss)
            return loss / n, logs

        batch_spec = P(axes)
        if use_ef:
            def island(params, residual, batch, rng):
                def local_loss(p, rrow):
                    return _tapped_loss(p, rrow, batch, rng)

                (_, logs), (grads, new_resid) = jax.value_and_grad(
                    local_loss, argnums=(0, 1), has_aux=True
                )(params, residual[0])
                return grads, _pmean_logs(logs), new_resid[None]

            return shard_map(
                island,
                mesh=self.mesh,
                in_specs=(P(), P(axes), batch_spec, P()),
                out_specs=(P(), P(), P(axes)),
                check_vma=False,
            )

        def island(params, batch, rng):
            def local_loss(p):
                return _tapped_loss(p, None, batch, rng)

            (_, logs), grads = jax.value_and_grad(
                local_loss, has_aux=True
            )(params)
            return grads, _pmean_logs(logs)

        return shard_map(
            island,
            mesh=self.mesh,
            in_specs=(P(), batch_spec, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )


def _batch_only_mesh(mesh) -> bool:
    """True when every mesh axis with extent > 1 is batch-parallel —
    the precondition for replicated-param per-device grad math."""
    return all(
        mesh.shape[a] == 1 or a in ("data", "fsdp")
        for a in mesh.axis_names
    )


def maybe_build_grad_sync(
    module: Any,
    mesh,
    cfg: Any,
    mode: str = "gspmd",
    zero_stage: int = 0,
    abstract_params: Any = None,
    overlap_segments: int = 0,
) -> Optional["GradSync"]:
    """Resolve a grad-comm request against the actual (mesh, strategy)
    shape.  Returns an active :class:`GradSync`, or ``None`` (full-width)
    — every downgrade warns with the reason, never silently.

    ``overlap_segments >= 1`` additionally asks for backward-overlapped
    sync (``grad_overlap_segments`` knob): the module must partition its
    params via ``grad_overlap_groups`` (parallel/overlap.py) — a module
    that can't (returns ``None`` / lacks the hook) warns and keeps the
    step-end sync, never silently changes schedule."""
    cfg = GradCommConfig.coerce(cfg)
    if cfg.mode == "full" or mesh is None:
        return None

    def _downgrade(reason: str) -> None:
        warnings.warn(
            f"grad_comm={cfg.mode!r} requested but {reason}; "
            "gradients sync at full width."
        )

    if mode != "gspmd":
        _downgrade(f"step mode {mode!r} is not 'gspmd'")
        return None
    if zero_stage >= 3:
        _downgrade(
            "zero_stage=3 shards params (quantized ZeRO-3 all-gather is "
            "the follow-on, not this path)"
        )
        return None
    if not _batch_only_mesh(mesh):
        _downgrade(
            f"mesh axes {dict(mesh.shape)} include model-parallel axes"
        )
        return None
    axes = shardlib.data_axes(mesh)
    n_shards = 1
    for a in axes:
        n_shards *= mesh.shape[a]
    if n_shards <= 1:
        return None  # nothing to sync — not worth a warning
    if cfg.dcn_only and jax.process_count() <= 1:
        _downgrade(
            "the mesh is single-host (ICI-only) and dcn_only=True "
            "(pass dcn_only=False to compress anyway)"
        )
        return None
    if abstract_params is None:
        abstract_params = jax.eval_shape(
            module.init_params, jax.random.PRNGKey(0)
        )
    plan = build_bucket_plan(
        abstract_params, n_shards, cfg.bucket_bytes, cfg.block_size
    )
    if plan.num_buckets == 0:
        _downgrade("the module has no parameters to sync")
        return None
    overlap = None
    if overlap_segments and overlap_segments >= 1:
        from ray_lightning_tpu.parallel import overlap as ovl

        groups_fn = getattr(module, "grad_overlap_groups", None)
        spec = (
            groups_fn(abstract_params, overlap_segments)
            if groups_fn is not None else None
        )
        if spec is None:
            warnings.warn(
                f"grad_overlap_segments={overlap_segments} requested but "
                f"{type(module).__name__} does not partition its params "
                "(grad_overlap_groups is missing or returned None); "
                "gradients sync at step end."
            )
        else:
            overlap = ovl.build_overlap_plan(
                spec, n_shards, cfg.bucket_bytes, cfg.block_size
            )
            if overlap.total_elems != plan.total_elems:
                # A partition that misses (or double-counts) params
                # would silently skip their sync — module bug, fail
                # loudly at build time.
                raise ValueError(
                    f"grad_overlap_groups covers {overlap.total_elems} "
                    f"elements but the module has {plan.total_elems} — "
                    "the groups must partition the whole param tree"
                )
    return GradSync(module, mesh, cfg, axes, n_shards, plan, overlap=overlap)
