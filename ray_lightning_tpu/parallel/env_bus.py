"""Central registry of every ``RLT_*`` environment knob.

One source of truth for the env bus: the knob's name, whether the
strategy layer FORWARDS it to spawned workers (remote workers — node
agents, Ray runtime_env — inherit the AGENT's env, not the driver's,
so a driver-side export that is not bridged here silently never
reaches the fleet; that exact bug class is why this registry exists),
and a one-line description.

Two consumers, which is the point:

* ``parallel/strategies.py`` builds its worker env bridge from
  :func:`forwarded_vars` — the forwarding list can no longer drift
  from the documented knob set;
* ``tools/rlt_lint`` (rule **RLT005**) statically cross-checks every
  literal ``os.environ``/``os.getenv`` read of an ``RLT_*`` name in
  the tree against this registry, so a new knob that someone forgets
  to register (and therefore to forward) fails lint instead of
  silently resolving to its default on every worker.

Adding a knob: one :class:`EnvKnob` line here.  ``forward=True`` puts
it on the worker bridge; ``forward=False`` documents why it is
driver-, agent-, or bench-local.  The linter parses this file with
``ast`` (no import), so keep entries as plain ``EnvKnob("NAME", ...)``
calls with a literal first argument.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

__all__ = ["EnvKnob", "KNOBS", "forwarded_vars", "registered_names"]


class EnvKnob(NamedTuple):
    name: str
    #: Bridged into every spawned worker's env (strategies layer)?
    forward: bool
    #: Where the knob is read / why it is (not) forwarded.
    doc: str


KNOBS: Tuple[EnvKnob, ...] = (
    # -- gradient-comm bus (parallel/grad_sync.py, worker-side) ----------
    EnvKnob("RLT_GRAD_COMM", True, "grad compression mode (int8_ef/full)"),
    EnvKnob("RLT_GRAD_BUCKET_MB", True, "all-reduce bucket size"),
    EnvKnob("RLT_GRAD_BLOCK", True, "int8 quantization block length"),
    EnvKnob("RLT_GRAD_DCN_ONLY", True, "compress only across DCN"),
    EnvKnob("RLT_GRAD_OVERLAP", True,
            "backward-overlapped grad sync: trunk segment count G "
            "(0/empty = step-end sync; parallel/overlap.py)"),
    # -- MPMD transport (mpmd/transfer.py, worker-side) ------------------
    EnvKnob("RLT_MPMD_WIRE_DTYPE", True,
            "pipeline DCN payload codec: f32/bf16/int8 or "
            "'act:X,grad:Y' (mpmd/transfer.py WireDtypeConfig)"),
    # -- telemetry bus (telemetry/runtime.py, worker-side) ---------------
    EnvKnob("RLT_TELEMETRY", True, "tier: off/cheap/full"),
    EnvKnob("RLT_TELEMETRY_SAMPLE", True, "step-stats sampling period"),
    EnvKnob("RLT_TELEMETRY_DIR", True, "export directory"),
    EnvKnob("RLT_TELEMETRY_PEAK", True, "device peak-memory probe"),
    EnvKnob("RLT_HEARTBEAT_S", True, "live-plane beat cadence (0=off)"),
    EnvKnob("RLT_FLIGHT_RECORDER", True, "crash-bundle output gate"),
    EnvKnob("RLT_PROGRAM_LEDGER", True,
            "program-ledger kill switch (0/off = bare jax.jit)"),
    EnvKnob("RLT_LOG_RING", True, "forwarded-log ring size"),
    # -- chaos plane (fault/inject.py, worker-side) ----------------------
    EnvKnob("RLT_FAULT", True, "deterministic fault grammar"),
    EnvKnob("RLT_FAULT_STATE", True, "exactly-once marker directory"),
    EnvKnob("RLT_DRAIN_SYNC_EVERY", True, "drain-agreement cadence"),
    # -- loop execution knobs (core/loop.py, worker-side) ----------------
    EnvKnob("RLT_MEGASTEP", True, "fused micro-steps per dispatch"),
    EnvKnob("RLT_UPDATE_SHARDING", True, "cross-replica sharded update"),
    # -- driver-side knobs (never bridged verbatim) ----------------------
    EnvKnob("RLT_COMPILE_CACHE", False,
            "bridged as JAX_COMPILATION_CACHE_DIR, not verbatim"),
    EnvKnob("RLT_ELASTIC_MIN_WORKERS", False, "governor floor (driver)"),
    EnvKnob("RLT_ELASTIC_GROW_AFTER_S", False, "grow-back arm (driver)"),
    EnvKnob("RLT_TPU_CHIPS_PER_HOST", False, "host-topology hint (driver)"),
    EnvKnob("RLT_BACKEND", False, "cluster backend selector (driver)"),
    EnvKnob("RLT_HOSTS", False, "static host list (driver)"),
    EnvKnob("RLT_AGENT_TOKEN", False, "node-agent auth (agent process)"),
    EnvKnob("RLT_SEGMENT_MIN_BYTES", False, "shm threshold (per-process)"),
    EnvKnob("RLT_DISABLE_KERNELS", False, "kernel-probe opt-out (local)"),
    EnvKnob("RLT_DISABLE_NATIVE", False, "native-ext opt-out (local)"),
    EnvKnob("RLT_LORA_BGMV", False,
            "force the multi-LoRA BGMV arm: xla|pallas (resolved once "
            "at engine/worker build; serving actors inherit the local "
            "env, so no strategy bridge)"),
    # -- monitor/prom knobs (telemetry/monitor.py from_env map) ----------
    EnvKnob("RLT_MONITOR_HANG_INTERVALS", False, "stall threshold"),
    EnvKnob("RLT_MONITOR_ABORT_S", False, "hang-abort deadline"),
    EnvKnob("RLT_MONITOR_STRAGGLER_LAG", False, "straggler lag steps"),
    EnvKnob("RLT_MONITOR_DIR", False, "monitor artifact directory"),
    EnvKnob("RLT_PROM_FILE", False, "OpenMetrics textfile path"),
    EnvKnob("RLT_PROM_PORT", False, "OpenMetrics localhost port"),
    # -- bench / entry-point knobs (never reach workers by design) -------
    EnvKnob("RLT_OPT_STATE_DTYPE", False, "bench opt-state arm"),
    EnvKnob("RLT_REMAT_POLICY", False, "bench remat arm"),
    EnvKnob("RLT_SPEC_K", False, "bench speculative width"),
    EnvKnob("RLT_PREFIX_CACHE", False, "bench prefix-cache arm gate"),
    EnvKnob("RLT_PREFIX_SHARE", False, "bench shared-prefix mix %"),
    EnvKnob("RLT_PREFILL_CHUNK", False, "bench chunked-prefill width"),
    EnvKnob("RLT_DISAGG_REPLICAS", False, "bench fleet width"),
    EnvKnob("RLT_DISAGG_PREFILL", False, "bench prefill workers"),
    EnvKnob("RLT_MAX_ADAPTERS", False, "bench multi-LoRA tenant count"),
    EnvKnob("RLT_DRYRUN_MPMD", False, "graft-entry mpmd flavor gate"),
    # -- SLO & capacity plane (serve entry points + router) --------------
    EnvKnob("RLT_SLO", False, "serve SLO burn-rate evaluator gate"),
    EnvKnob("RLT_CAPACITY", False, "serve capacity/headroom oracle gate"),
    EnvKnob("RLT_TS_INTERVAL_S", False, "time-series store bin width"),
    EnvKnob("RLT_HEADROOM_ROUTING", False,
            "router placement tie-break on reported headroom (resolved "
            "once at router build; router is driver/agent-local)"),
    # -- serving-plane resilience (ISSUE 19) -----------------------------
    EnvKnob("RLT_MIGRATE_ON_DRAIN", True,
            "planned-drain live KV migration gate (0 = recompute "
            "failover only; read by the replica runner, so actor "
            "replicas need the bridge)"),
    EnvKnob("RLT_BROWNOUT", False,
            "router overload brownout ladder gate (resolved once at "
            "router build; router is driver/agent-local)"),
    EnvKnob("RLT_HEDGE", False,
            "client hedged-resubmit gate (ServeClient RetryPolicy; "
            "client-local by definition)"),
    EnvKnob("RLT_RETRY_MAX", False,
            "client retry attempts on typed rejections (client-local)"),
    EnvKnob("RLT_RETRY_BACKOFF_S", False,
            "client retry backoff base seconds (client-local)"),
    EnvKnob("RLT_SERVE_CHAOS", False,
            "bench_serve: skip the migration-vs-failover serve_chaos "
            "phase when 0 (bench-process-local gate)"),
)


def forwarded_vars() -> Tuple[str, ...]:
    """Names the strategy layer bridges into every worker's env."""
    return tuple(k.name for k in KNOBS if k.forward)


def registered_names() -> Tuple[str, ...]:
    """Every registered knob name (the RLT005 lint contract)."""
    return tuple(k.name for k in KNOBS)
