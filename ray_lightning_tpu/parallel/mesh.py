"""Mesh bootstrap + host/rank mapping.

≙ the reference's rendezvous + rank plumbing, re-done the JAX way:

* coordinator brokering (driver picks worker-0's IP + a free port and
  broadcasts it) ≙ ``MASTER_ADDR``/``MASTER_PORT`` setup at reference
  ``ray_ddp.py:215-228``, but feeding ``jax.distributed.initialize``
  instead of a torch TCPStore;
* ``compute_host_ranks`` ≙ ``RayPlugin.get_local_ranks``'s IP-grouped
  node/local rank map (reference ``ray_ddp.py:291-315``);
* mesh construction replaces process groups entirely: collectives are
  compiler-scheduled over the mesh axes (ICI within a slice, DCN across
  slices), no NCCL communicator objects exist.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "compute_host_ranks",
    "partition_host_chips",
    "bootstrap_distributed",
    "build_mesh",
    "MeshSpec",
]


def compute_host_ranks(
    node_ips: Sequence[str],
) -> Dict[int, Tuple[int, int]]:
    """Map global worker rank → (node_rank, local_rank).

    Workers on the same IP share a node; node ranks are assigned in order
    of first appearance, local ranks in submission order — byte-for-byte
    the semantics of reference ``get_local_ranks`` (``ray_ddp.py:291-315``)
    so multi-worker-per-node placements behave identically.
    """
    node_order: List[str] = []
    local_counts: Dict[str, int] = collections.defaultdict(int)
    mapping: Dict[int, Tuple[int, int]] = {}
    for global_rank, ip in enumerate(node_ips):
        if ip not in node_order:
            node_order.append(ip)
        node_rank = node_order.index(ip)
        local_rank = local_counts[ip]
        local_counts[ip] += 1
        mapping[global_rank] = (node_rank, local_rank)
    return mapping


def partition_host_chips(
    node_ips: Sequence[str],
    chips_per_host: int = 4,
) -> Dict[int, Optional[str]]:
    """Disjoint per-worker ``TPU_VISIBLE_CHIPS`` values for co-located
    workers.

    ≙ the reference's per-node ``CUDA_VISIBLE_DEVICES`` computation
    (``ray_ddp.py:230-274``, tested ``test_ddp_gpu.py:85-122``) — but
    where NCCL wants every co-located worker to see the node's full GPU
    union, a TPU host's chips must be PARTITIONED: each PJRT process
    exclusively owns its chips, so k workers sharing a host each get a
    disjoint ``chips_per_host / k`` slice (by local rank, in submission
    order).

    Returns global rank → chips string (``"0,1"``) for workers that share
    a host, or ``None`` for a host's sole worker (no constraint: it owns
    every chip, and clobbering an externally-set visibility would be
    wrong).
    """
    ranks = compute_host_ranks(node_ips)
    counts: Dict[str, int] = collections.Counter(node_ips)
    out: Dict[int, Optional[str]] = {}
    for global_rank, ip in enumerate(node_ips):
        k = counts[ip]
        if k <= 1:
            out[global_rank] = None
            continue
        if chips_per_host % k:
            raise ValueError(
                f"{k} workers share host {ip} but {chips_per_host} chips "
                f"per host do not divide evenly; use a worker count that "
                f"divides the chip count or one worker per host."
            )
        per = chips_per_host // k
        _, local_rank = ranks[global_rank]
        out[global_rank] = ",".join(
            str(c) for c in range(local_rank * per, (local_rank + 1) * per)
        )
    return out


def bootstrap_distributed(
    coordinator_address: Optional[str],
    num_processes: int,
    process_id: int,
) -> None:
    """Join the multi-controller JAX runtime (worker-side).

    ≙ ``torch.distributed.init_process_group`` at reference
    ``ray_ddp.py:430-433``; the coordinator address is brokered by the
    driver exactly as MASTER_ADDR was.  Single-process runs skip
    initialization entirely (the driver stays outside the mesh — SURVEY §7
    hard-part #2: the laptop-driver property).
    """
    if num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


class MeshSpec:
    """Declarative mesh request: axis names + sizes, -1 = infer.

    Examples::

        MeshSpec()                          # 1-D data mesh over all devices
        MeshSpec(axes={"data": -1})
        MeshSpec(axes={"data": 2, "fsdp": 2, "tensor": 2})
    """

    def __init__(self, axes: Optional[Dict[str, int]] = None):
        self.axes = dict(axes or {"data": -1})
        inferred = [k for k, v in self.axes.items() if v == -1]
        if len(inferred) > 1:
            raise ValueError(f"Only one axis may be -1 (got {inferred})")

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axes.keys())

    def resolve(self, num_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        known = 1
        infer_key = None
        for k, v in sizes.items():
            if v == -1:
                infer_key = k
            else:
                known *= v
        if infer_key is not None:
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {known} ({sizes})"
                )
            sizes[infer_key] = num_devices // known
        else:
            total = 1
            for v in sizes.values():
                total *= v
            if total != num_devices:
                raise ValueError(
                    f"Mesh {sizes} wants {total} devices, have {num_devices}"
                )
        return sizes


def build_mesh(spec: Optional[MeshSpec] = None, devices=None):
    """Construct a ``jax.sharding.Mesh`` over the (global) device set.

    On a multi-host run every process calls this AFTER
    :func:`bootstrap_distributed`; ``jax.devices()`` then returns the
    global device list and all hosts build an identical mesh —
    the SPMD analogue of every worker joining one process group.
    """
    import jax
    import numpy as np
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[name] for name in spec.axis_names)
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices)
        )
    except (ValueError, AssertionError):
        # Fallback for virtual/CPU devices where topology hints are absent.
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, spec.axis_names)
