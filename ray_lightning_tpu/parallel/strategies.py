"""Training strategies: the remote-execution lifecycle (the framework's heart).

≙ the reference's L5 plugin layer (``/root/reference/ray_lightning/ray_ddp.py:66-565``,
``ray_horovod.py:35-239``, ``ray_ddp_sharded.py:17-34``): a strategy owns

1. **worker launch** — one actor per TPU host with resource reservation and
   ``init_hook`` (≙ ``_create_worker``/``setup``, ``ray_ddp.py:183-195``);
2. **rendezvous brokering** — driver obtains worker-0's IP + a free port
   *on that node* and broadcasts it as the ``jax.distributed`` coordinator
   (≙ ``_setup_env_vars`` MASTER_ADDR/PORT, ``ray_ddp.py:215-228``);
3. **task shipping** — the (module, datamodule, config, callbacks) package
   is serialized once into the object store and every worker materializes
   its own copy (≙ ``ray.put(model)``, ``ray_ddp.py:339-353``);
4. **the remote loop** — workers run the shared fit loop under a device
   mesh; gradient sync is XLA collectives compiled into the step
   (no process-group objects, no NCCL — SURVEY §2.2);
5. **result recovery** — driver pumps the queue, adopts rank-0's state
   stream/metrics/best-path, tears actors down
   (≙ ``post_dispatch``, ``ray_ddp.py:362-401``).

Flavor map (≙ the reference's three plugins):

* :class:`RayStrategy` — GSPMD data parallel (≙ ``RayPlugin`` DDP).
* :class:`HorovodRayStrategy` — explicit per-device collectives via
  ``shard_map`` + ``lax.pmean`` (≙ ``HorovodRayPlugin``'s ring allreduce).
* :class:`RayShardedStrategy` — ZeRO optimizer/param sharding as
  ``NamedSharding`` annotations (≙ ``RayShardedPlugin``/FairScale OSS).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import shutil
import time
import uuid
import warnings
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu import session as session_mod
from ray_lightning_tpu.cluster import backend as backend_mod
from ray_lightning_tpu.cluster import rpc
from ray_lightning_tpu.cluster.actor import ActorDiedError, RemoteError
from ray_lightning_tpu.core.loop import (
    FitConfig,
    _normalize_megastep,
    _normalize_update_sharding,
    run_eval,
    run_fit,
    run_predict,
)
from ray_lightning_tpu.fault import drain as drain_mod
from ray_lightning_tpu.parallel import env_bus
from ray_lightning_tpu.parallel.overlap import normalize_grad_overlap
from ray_lightning_tpu.fault.drain import PreemptedError
from ray_lightning_tpu.util import process_results

log = logging.getLogger(__name__)

# Distinguishes "no resize happened yet" from "last resize resumed from
# scratch (None)" in the flap guard's progress comparison.
_RESIZE_CKPT_UNSET = object()

__all__ = [
    "TpuStrategy",
    "LocalStrategy",
    "RayStrategy",
    "HorovodRayStrategy",
    "RayShardedStrategy",
    "MpmdStrategy",
    # Reference-name aliases for drop-in familiarity:
    "RayPlugin",
    "HorovodRayPlugin",
    "RayShardedPlugin",
]


# ---------------------------------------------------------------------------
# Worker-side entry (top-level: importable in actor children)
# ---------------------------------------------------------------------------

def _remote_latest_restart_checkpoint(restart_dir: str) -> Dict[str, Any]:
    """Runs on worker 0 (or driver-side on a shared filesystem): newest
    COMPLETE **and verified** restart/drain checkpoint on its node.

    Sharded checkpoints (directories) count only once their META marker
    exists — a crash mid-write must never be resumed from.  Candidates
    are ordered newest-first by completion time (META mtime — drain and
    epoch checkpoints interleave, so name order alone cannot rank them)
    and each is integrity-verified (``sharded_ckpt.verify_checkpoint``):
    a torn or bit-flipped newest checkpoint is WALKED PAST to the
    previous good one instead of bricking every restart attempt.

    Returns ``{"path": newest_verified_or_None, "corrupt": [...]}`` —
    the corrupt list feeds the driver's ``ckpt_corrupt`` telemetry.
    """
    from ray_lightning_tpu.utils.sharded_ckpt import (
        list_restart_candidates,
        verify_checkpoint,
    )

    corrupt: List[Dict[str, Any]] = []
    for _, _, _, path in list_restart_candidates(restart_dir):
        problems = verify_checkpoint(path)
        if not problems:
            return {"path": path, "corrupt": corrupt}
        corrupt.append({"path": path, "problems": problems[:3]})
    return {"path": None, "corrupt": corrupt}


def _remote_find_free_port() -> int:
    """Free port on the *worker's* node (≙ reference ``ray_ddp.py:31-35``,
    executed on worker 0 just like ``_setup_env_vars`` does)."""
    return rpc.find_free_port()


def _execute_remote(task_ref, global_rank: int, queue_handle) -> Dict[str, Any]:
    """Worker-side driver of one training run (≙ ``RayPlugin.execute_remote``,
    reference ``ray_ddp.py:443-523``).

    Order of operations mirrors the reference: install session → join the
    distributed runtime (collective boundary) → build the mesh → run the
    stage → rank 0 returns the heavy result package.
    """
    task = task_ref.get()
    world_size = task["world_size"]

    session_mod.init_session(
        rank=global_rank,
        queue=queue_handle,
        num_workers=world_size,
    )
    try:
        from ray_lightning_tpu.parallel.mesh import (
            MeshSpec,
            bootstrap_distributed,
            build_mesh,
        )

        # Chaos injection point — BEFORE the collective boundary, so a
        # spawn-pinned fault (crash / lose_worker) kills this worker
        # while its peers can still be detected + killed by the driver
        # instead of wedging inside jax.distributed.initialize.
        from ray_lightning_tpu.fault import inject as _chaos

        _chaos.set_rank(global_rank)
        _chaos.fire("spawn", rank=global_rank)

        # ═══ collective boundary (≙ init_process_group, ray_ddp.py:430) ═══
        bootstrap_distributed(
            task.get("coordinator"), world_size, global_rank
        )
        mesh = build_mesh(MeshSpec(task.get("mesh_axes")))

        sess = session_mod.get_session()
        sess.mesh = mesh
        import jax

        sess.local_devices = jax.local_devices()

        kind = task["kind"]
        common = dict(
            module=task["module"],
            datamodule=task["datamodule"],
            config=task["config"],
            global_rank=global_rank,
            world_size=world_size,
            mesh=mesh,
        )
        if kind == "fit":
            try:
                return run_fit(
                    callbacks=task["callbacks"],
                    mode=task["mode"],
                    zero_stage=task["zero_stage"],
                    grad_comm=task.get("grad_comm"),
                    telemetry=task.get("telemetry"),
                    queue=queue_handle,
                    **common,
                )
            except PreemptedError:
                # A drain is an orderly exit, not a crash: the loop
                # already wrote its drain checkpoint and retired the
                # live plane — no flight bundle.
                raise
            except BaseException as err:
                # Crash forensics: persist the flight bundle (spans,
                # step stats, logs, stacks — telemetry/flight_recorder)
                # and announce its path on the queue BEFORE the
                # exception travels back as a bare traceback.  No-op
                # when telemetry is off or no recorder is armed.
                from ray_lightning_tpu.telemetry.flight_recorder import (
                    record_active_crash,
                )

                record_active_crash(err)
                raise
        if kind in ("validation", "test"):
            return run_eval(
                callbacks=task["callbacks"],
                kind=kind,
                mode=task["mode"],
                zero_stage=task["zero_stage"],
                params_stream=task.get("params_stream"),
                ckpt_path=task.get("ckpt_path"),
                telemetry=task.get("telemetry"),
                queue=queue_handle,
                **common,
            )
        if kind == "predict":
            return run_predict(
                zero_stage=task["zero_stage"],
                params_stream=task.get("params_stream"),
                ckpt_path=task.get("ckpt_path"),
                telemetry=task.get("telemetry"),
                **common,
            )
        raise ValueError(f"Unknown stage kind {task['kind']!r}")
    finally:
        session_mod.shutdown_session()
        if world_size > 1:
            # Orderly disconnect from the coordination service — without
            # this, the first worker to exit is seen as "died" and the
            # service fatally terminates its peers mid-teardown.
            try:
                import jax

                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass


# ---------------------------------------------------------------------------
# Strategies
# ---------------------------------------------------------------------------

class TpuStrategy:
    """Base strategy: worker lifecycle + execution loop.

    Constructor signature mirrors ``RayPlugin.__init__`` (reference
    ``ray_ddp.py:118-171``): ``num_workers`` (hosts), per-worker resources,
    ``init_hook``, ``resources_per_worker`` overriding the convenience
    flags.  TPU-specific additions: ``mesh_axes`` (device mesh layout) and
    the compute ``mode``/``zero_stage`` knobs.
    """

    mode: str = "gspmd"
    zero_stage: int = 0
    # Whether this strategy's world may be elastically resized; subclasses
    # with a STRUCTURAL world (MpmdStrategy: the layer split is baked into
    # every stage's program) set False, and the fleet-wide RLT_ELASTIC_*
    # env bus is then ignored instead of crashing their constructors.
    supports_elastic_resize: bool = True

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: int = 1,
        use_tpu: bool = True,
        init_hook: Optional[Callable[[], None]] = None,
        resources_per_worker: Optional[Dict[str, float]] = None,
        backend: Optional[str] = None,
        mesh_axes: Optional[Dict[str, int]] = None,
        env_per_worker: Optional[Dict[str, str]] = None,
        max_restarts: int = 0,
        restart_every_n_epochs: int = 1,
        restart_window_s: float = 3600.0,
        restart_backoff_s: float = 1.0,
        restart_backoff_max_s: float = 60.0,
        grad_comm=None,
        telemetry=None,
        monitor=None,
        megastep=None,
        update_sharding=None,
        grad_overlap_segments=None,
        elastic_min_workers: Optional[int] = None,
        elastic_grow_after_s: Optional[float] = None,
        elastic_capacity_fn: Optional[Callable[[], int]] = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers
        self.num_cpus_per_worker = num_cpus_per_worker
        self.use_tpu = use_tpu
        self.init_hook = init_hook
        # resources_per_worker overrides the convenience flags (reference
        # resolution matrix, ray_ddp.py:128-140, tested test_ddp.py:138-176).
        resources = dict(resources_per_worker or {})
        self.num_cpus_per_worker = int(
            resources.pop("CPU", num_cpus_per_worker)
        )
        if "TPU" in resources:
            self.use_tpu = resources.pop("TPU") > 0
        self.additional_resources_per_worker = resources
        self.backend_name = backend
        self.mesh_axes = mesh_axes
        # Gradient-communication config (mode string, dict, or
        # GradCommConfig; None = RLT_GRAD_COMM env bus / full-width).
        # Validated eagerly so a typo'd mode fails at construction, not
        # minutes later on a worker.
        if grad_comm is not None:
            from ray_lightning_tpu.parallel.grad_sync import GradCommConfig

            grad_comm = GradCommConfig.coerce(grad_comm)
        self.grad_comm = grad_comm
        # Telemetry tier/knobs (tier string, dict, or TelemetryConfig;
        # None = RLT_TELEMETRY env bus / cheap default).  Same eager
        # validation discipline as grad_comm: a typo'd tier fails here.
        if telemetry is not None:
            from ray_lightning_tpu.telemetry import TelemetryConfig

            telemetry = TelemetryConfig.coerce(telemetry)
        self.telemetry = telemetry
        # Live-monitor knobs (dict or MonitorConfig; None = RLT_MONITOR_*
        # env bus at fit time).  Validated eagerly like grad_comm, but
        # the RAW value is kept: a dict without heartbeat_s must inherit
        # the telemetry cadence at fit time — coercing it to a frozen
        # MonitorConfig here would bake in the 5s default and make a
        # fast-heartbeat run watchdog at the slow default budget.
        if monitor is not None:
            from ray_lightning_tpu.telemetry import MonitorConfig

            MonitorConfig.coerce(monitor)
        self.monitor = monitor
        # Megastep stride length (core/loop.py megastep mode: K fused
        # micro-steps per jitted dispatch).  None defers to the
        # Trainer's knob / the RLT_MEGASTEP env bus / "auto"; validated
        # eagerly like every other strategy knob.
        _normalize_megastep(megastep)
        self.megastep = megastep
        # Cross-replica sharded weight update (core/loop.py
        # update_sharding mode).  None defers to the Trainer's knob /
        # the RLT_UPDATE_SHARDING env bus / "auto"; validated eagerly
        # like every other strategy knob.
        _normalize_update_sharding(update_sharding)
        self.update_sharding = update_sharding
        # Backward-overlapped grad sync (core/loop.py + parallel/
        # overlap.py: G trunk segments, custom_vjp grad taps).  None
        # defers to the Trainer's knob / the RLT_GRAD_OVERLAP env bus /
        # off; validated eagerly like every other strategy knob.
        normalize_grad_overlap(grad_overlap_segments)
        self.grad_overlap_segments = grad_overlap_segments
        self.env_per_worker = dict(env_per_worker or {})
        # Persistent XLA compilation cache (RLT_COMPILE_CACHE=dir): the
        # first GPT-2-scale compile costs 20-40s on this platform; a
        # shared on-disk cache amortizes it across worker respawns
        # (elastic restarts), tuner trials, and sessions.  Forwarded as
        # JAX_COMPILATION_CACHE_DIR, which must land BEFORE the worker's
        # first jax import — exactly the pre-exec env contract actors
        # already provide (≙ the reference's env bus, ray_ddp.py:215-228).
        cache_dir = os.environ.get("RLT_COMPILE_CACHE")
        if cache_dir and "JAX_COMPILATION_CACHE_DIR" not in self.env_per_worker:
            self.env_per_worker["JAX_COMPILATION_CACHE_DIR"] = cache_dir
            # Mirror the driver-side hook's threshold: without this,
            # worker compiles under jax's ~1s default are silently not
            # cached — exactly the nondeterminism the knob exists to
            # remove.
            self.env_per_worker.setdefault(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0"
            )
        # Worker env bus: every forward-marked knob in the central
        # registry (parallel/env_bus.py) rides the same bridge
        # RLT_COMPILE_CACHE does — remote workers (node agents, Ray
        # runtime_env) inherit the AGENT's env, not the driver's, so
        # without this a driver-side RLT_GRAD_COMM would silently
        # resolve to full-width on exactly the multi-host topology
        # compression targets.  The knob list lives in ONE place; the
        # rlt_lint RLT005 rule cross-checks every env read against it.
        for var in env_bus.forwarded_vars():
            val = os.environ.get(var)
            if val is not None:
                self.env_per_worker.setdefault(var, val)
        # Elastic fault tolerance (extends the reference, which only
        # fails fast — SURVEY §5 "failure detection: ABSENT"): on worker
        # death during fit, respawn the worker set up to ``max_restarts``
        # times and resume from the newest restart checkpoint.
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if restart_every_n_epochs < 1:
            raise ValueError("restart_every_n_epochs must be >= 1")
        if restart_window_s <= 0:
            raise ValueError("restart_window_s must be > 0")
        if restart_backoff_s < 0 or restart_backoff_max_s < 0:
            raise ValueError("restart backoff times must be >= 0")
        self.max_restarts = max_restarts
        self.restart_every_n_epochs = restart_every_n_epochs
        # Restart governance (docs/FAULT_TOLERANCE.md): the failure
        # budget is a SLIDING WINDOW (max_restarts per restart_window_s),
        # not a per-fit lifetime count — a week-long fit may absorb many
        # spread-out failures, while a flapping host still exhausts the
        # budget within the hour it flaps.  Respawns back off
        # exponentially with jitter so a correlated outage doesn't
        # hammer the scheduler in lockstep.
        self.restart_window_s = restart_window_s
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.restarts_used = 0
        # Preemption drains recover WITHOUT consuming the failure budget
        # (they are the normal case, not an error — Podracer); counted
        # separately so dashboards can tell churn from failure.
        self.preempt_restarts_used = 0
        # Elastic world sizing (docs/FAULT_TOLERANCE.md "Elastic
        # resume"): with ``elastic_min_workers`` set, the governor may
        # deliberately respawn with M < N SURVIVORS when the fleet lost
        # capacity — a preempted host becomes a shrink, not a wait —
        # and grows back once capacity has been available again for
        # ``elastic_grow_after_s`` seconds (a deliberate drain at the
        # next sync boundary, budget-free).  Capacity comes from
        # ``elastic_capacity_fn`` (a fleet-API probe in production;
        # default: the chaos plane's lost-worker markers, so the whole
        # path is deterministically testable via ``lose_worker@...``).
        if elastic_min_workers is None and self.supports_elastic_resize:
            env = os.environ.get("RLT_ELASTIC_MIN_WORKERS")
            elastic_min_workers = int(env) if env else None
            if elastic_min_workers is not None:
                # The env bus serves fleets of MIXED sizes: clamp into
                # [1, num_workers] rather than reject, so one exported
                # floor never crashes a strategy it doesn't fit.
                elastic_min_workers = min(
                    max(elastic_min_workers, 1), num_workers
                )
        if elastic_grow_after_s is None and self.supports_elastic_resize:
            env = os.environ.get("RLT_ELASTIC_GROW_AFTER_S")
            elastic_grow_after_s = float(env) if env else None
        if elastic_min_workers is not None and not (
                1 <= elastic_min_workers <= num_workers):
            raise ValueError(
                f"elastic_min_workers must be in [1, num_workers="
                f"{num_workers}], got {elastic_min_workers}"
            )
        if elastic_grow_after_s is not None and elastic_grow_after_s < 0:
            raise ValueError("elastic_grow_after_s must be >= 0")
        self.elastic_min_workers = elastic_min_workers
        self.elastic_grow_after_s = elastic_grow_after_s
        self.elastic_capacity_fn = elastic_capacity_fn
        # The CURRENT world size: num_workers is the requested ceiling,
        # active_workers what the governor is actually running.
        self.active_workers = num_workers
        self.resizes_used = 0
        self.last_resize_recover_s: Optional[float] = None
        # Flap-guard progress proxy: a SENTINEL, not None — the first
        # shrink of a fit with no checkpoint yet (resume None) must not
        # pre-seed the streak.
        self._last_resize_ckpt: Any = _RESIZE_CKPT_UNSET
        self._resize_streak = 0
        self._grow_pending = False
        self._capacity_ok_since: Optional[float] = None
        # Recovery events of the fit in flight (backoff delays, restart
        # attempts, checkpoint-corruption fallbacks, preempt restarts):
        # seeded into each attempt's RunMonitor so the final
        # ``trainer.monitor_report`` tells the whole story across
        # respawns, not just the last attempt's.
        self.recovery_events: List[Dict[str, Any]] = []
        self._carried_events: List[Dict[str, Any]] = []
        self._last_monitor = None
        self._drain_broadcast = False
        self._drain_broadcast_at = 0.0

        self._backend: Optional[backend_mod.ClusterBackend] = None
        self._workers: list = []

    # -- rank/world properties (driver side; ≙ ray_ddp.py:525-541) ----------
    @property
    def world_size(self) -> int:
        # The governor's CURRENT size: equals num_workers unless an
        # elastic resize shrank (or re-grew) the fleet mid-fit.
        return self.active_workers

    @property
    def global_rank(self) -> int:
        return 0  # the driver never trains (≙ _is_remote=False branch)

    @property
    def is_distributed(self) -> bool:
        return True

    # -- lifecycle ----------------------------------------------------------
    def setup(self, trainer) -> None:
        """Create workers + run init_hook (≙ ``RayPlugin.setup``,
        reference ``ray_ddp.py:191-195``)."""
        if self._workers:
            return
        # A backend *instance* stays owned by the caller (it may span
        # several trainers); teardown only shuts down backends we built.
        self._owns_backend = not isinstance(
            self.backend_name, backend_mod.ClusterBackend
        )
        self._backend = backend_mod.get_backend(self.backend_name)
        self._spawn_workers()

    def _spawn_workers(self) -> None:
        # Generation-unique names: a Ray named actor is deregistered
        # asynchronously after ray.kill, so a respawn reusing the same
        # name races the teardown.
        gen = getattr(self, "_spawn_generation", 0)
        self._spawn_generation = gen + 1
        suffix = "" if gen == 0 else f"-r{gen}"
        for i in range(self.active_workers):
            worker = self._backend.create_actor(
                name=f"rlt-worker-{i}{suffix}",
                env=self.env_per_worker or None,
                num_cpus=self.num_cpus_per_worker,
                resources=self.additional_resources_per_worker or None,
            )
            self._workers.append(worker)
        if self.use_tpu:
            self._partition_host_chips()
        if self.init_hook is not None:
            futures = [
                w.submit(self.init_hook) for w in self._workers
            ]
            for f in futures:
                f.result()

    def _partition_host_chips(self) -> None:
        """Split ``TPU_VISIBLE_CHIPS`` between co-located workers.

        ≙ reference ``_setup_env_vars``'s per-node device-visibility push
        (``ray_ddp.py:230-274``) with TPU partition semantics (each PJRT
        process must own its chips exclusively — see
        :func:`..mesh.partition_host_chips`).  Pushed BEFORE the worker's
        first jax import (workers import jax lazily when the task runs),
        so visibility is in place at PJRT init.  Sole-owner hosts are
        left untouched.
        """
        from ray_lightning_tpu.parallel.mesh import partition_host_chips

        ips = [w.get_node_ip() for w in self._workers]
        chips_per_host = int(os.environ.get("RLT_TPU_CHIPS_PER_HOST", 4))
        try:
            chip_map = partition_host_chips(ips, chips_per_host)
        except ValueError as err:
            # CPU-simulated meshes co-locate freely; on real TPU an
            # un-partitionable layout will fail at PJRT init anyway, with
            # this warning naming the cause first.
            warnings.warn(f"TPU chip partitioning skipped: {err}")
            return
        for rank, worker in enumerate(self._workers):
            chips = chip_map.get(rank)
            if chips is not None:
                worker.set_env_vars({"TPU_VISIBLE_CHIPS": chips})

    def _kill_workers(self, timeout: Optional[float] = None,
                      why: str = "teardown") -> None:
        """Kill every current worker.  Failures are expected (some are
        already dead) but never SILENT: an unkillable worker is a zombie
        holding TPU chips, and the debug log must say which rank."""
        for rank, w in enumerate(self._workers):
            try:
                if timeout is None:
                    w.kill()
                else:
                    w.kill(timeout=timeout)
            except Exception as e:  # noqa: BLE001 - already-dead is fine
                log.debug(
                    "%s: kill of worker rank %d (%s) failed: %r",
                    why, rank, getattr(w, "name", "?"), e,
                )
        # Crashed/killed workers (kill -9, monitor aborts, respawns)
        # can't run their own teardown: sweep their orphaned shared-
        # memory segments here so elastic restarts don't leak tmpfs
        # fit-over-fit (ProcessActor.kill sweeps too; this covers
        # backend adapters whose kill path never reaches it).
        try:
            from ray_lightning_tpu.cluster.shm import sweep_stale_segments

            swept = sweep_stale_segments()
            if swept:
                log.debug("%s: swept %d stale shm segments", why, swept)
        except Exception as e:  # noqa: BLE001 - janitorial only
            log.debug("%s: shm sweep failed: %r", why, e)

    def _respawn_workers(self) -> None:
        """Kill every current worker (peers of a dead one may be stuck in
        a collective forever) and start a fresh set."""
        self._kill_workers(why="respawn")
        self._workers = []
        self._spawn_workers()

    def _broker_coordinator(self) -> Optional[str]:
        """Worker-0-node coordinator address (≙ MASTER_ADDR/PORT brokering,
        reference ``ray_ddp.py:215-228``)."""
        if self.active_workers <= 1:
            return None
        if isinstance(self._backend, backend_mod.LocalBackend):
            # All actors share this host; loopback is always routable
            # (the NIC address may be NAT'd/unroutable in sandboxes).
            ip = "127.0.0.1"
        else:
            ip = self._workers[0].get_node_ip()
        port = self._workers[0].execute(_remote_find_free_port)
        return f"{ip}:{port}"

    def run(
        self,
        kind: str,
        module,
        datamodule,
        config: FitConfig,
        callbacks: List,
        trainer=None,
        params_stream: Optional[bytes] = None,
        ckpt_path: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """The execution loop (≙ ``RayPlugin.execution_loop``,
        reference ``ray_ddp.py:317-360``): ship → submit → pump → collect.

        With ``max_restarts > 0`` and ``kind="fit"``, worker death does not
        crash the fit: the whole worker set is respawned — after an
        exponential, jittered backoff, within a sliding per-
        ``restart_window_s`` failure budget — and training resumes from
        the newest VERIFIED restart checkpoint (corrupt ones are walked
        past; at most ``restart_every_n_epochs`` epochs of work are
        lost).  A preemption drain (:class:`PreemptedError`) restarts
        from its step-granular drain checkpoint WITHOUT consuming the
        failure budget — unless the drain request came from the driver
        itself (the driver is being preempted too), in which case it
        re-raises cleanly with the checkpoint named.
        """
        assert self._backend is not None, "setup() must run first"
        if config.megastep is None and self.megastep is not None:
            # The strategy's megastep knob fills the unset Trainer
            # default (an explicit Trainer(megastep=...) always wins).
            config = dataclasses.replace(config, megastep=self.megastep)
        if (config.update_sharding is None
                and self.update_sharding is not None):
            config = dataclasses.replace(
                config, update_sharding=self.update_sharding
            )
        if (config.grad_overlap_segments is None
                and self.grad_overlap_segments is not None):
            config = dataclasses.replace(
                config, grad_overlap_segments=self.grad_overlap_segments
            )
        elastic = self.max_restarts > 0 and kind == "fit"
        if elastic and config.restart_every_n_epochs is None:
            # The strategy's cadence fills the unset default wherever the
            # checkpoints land (caller-provided restart_dir included); an
            # explicit Trainer cadence always wins.
            config = dataclasses.replace(
                config, restart_every_n_epochs=self.restart_every_n_epochs
            )
        restart_dir = None
        if elastic and config.restart_dir is None:
            restart_dir = os.path.join(
                config.default_root_dir,
                f".rlt-restart-{uuid.uuid4().hex[:8]}",
            )
            config = dataclasses.replace(config, restart_dir=restart_dir)
        fail_times: List[float] = []   # budget-consuming failures
        last_preempt_step = -1
        preempt_streak = 0
        # Driver-side preemption: SIGTERM/SIGINT on the DRIVER while it
        # pumps results is forwarded to every worker over the control
        # lane (see _pump_tick), so the fleet drains as one.
        drain_installed = False
        preserve_scratch = False  # a raised PreemptedError names its
        # drain checkpoint — deleting the scratch dir would orphan it
        if kind == "fit":
            # Per-FIT recovery state: an eval/predict after a recovered
            # fit must not wipe the fit's recovery record.
            self.recovery_events = []
            self._carried_events = []
            self._last_monitor = None
            self._drain_broadcast = False
            self._drain_broadcast_at = 0.0
            self._grow_pending = False
            self._capacity_ok_since = None
            self._resize_streak = 0
            self._last_resize_ckpt = _RESIZE_CKPT_UNSET
            drain_mod.reset_drain()
            drain_mod.set_fit_active(True)
            drain_installed = drain_mod.install_signal_handlers()
        try:
            while True:
                try:
                    return self._run_once(
                        kind, module, datamodule, config, callbacks,
                        trainer=trainer, params_stream=params_stream,
                        ckpt_path=ckpt_path,
                    )
                except PreemptedError as err:
                    self._capture_attempt_events()
                    t_recover = time.monotonic()
                    grow_drain = self._grow_pending
                    self._grow_pending = False
                    if (not elastic or self._drain_broadcast
                            or drain_mod.drain_requested()):
                        # No elastic recovery, or the DRIVER itself is
                        # being preempted: a clean resumable raise — the
                        # error names the drain checkpoint.
                        preserve_scratch = err.checkpoint is not None
                        raise
                    # Flap guard: consecutive preemption recoveries that
                    # make no forward progress mean the host/quota is
                    # flapping — budget-free must not mean infinite.
                    # Grow drains ride the same guard: a grow that never
                    # advances the step cannot keep draining the fit.
                    step = int(getattr(err, "step", 0) or 0)
                    preempt_streak = (
                        preempt_streak + 1 if step <= last_preempt_step
                        else 0
                    )
                    last_preempt_step = step
                    if preempt_streak >= 2:
                        preserve_scratch = err.checkpoint is not None
                        raise
                    self.preempt_restarts_used += 1
                    # World sizing for the next attempt: a preemption
                    # may shrink the fleet (capacity lost with the
                    # drained host) or — on a deliberate grow drain —
                    # re-expand toward num_workers.
                    target, rejected = self._elastic_resize_decision()
                    if rejected:
                        preserve_scratch = err.checkpoint is not None
                        self._record_resize_rejected(target)
                        raise
                    # Elastic fits always have restart_dir set, and the
                    # drain checkpoint lands inside it — so verified
                    # discovery alone decides the resume point (the
                    # error's own checkpoint claim is the same path,
                    # already verified or rejected by discovery).
                    info = self._discover_resume(config)
                    resume = info["path"]
                    self._record_recovery(
                        "preempt_restart",
                        message=(
                            f"preemption drain at micro_step {step} "
                            f"({err.reason or 'requested'}); respawning "
                            f"without consuming the restart budget"
                        ),
                        ckpt=resume or "",
                    )
                    warnings.warn(
                        f"Preemption drain ({err}); elastic respawn "
                        f"(budget untouched), resuming from "
                        f"{resume or 'scratch'}."
                    )
                    self._respawn_resized(
                        target, t_recover, resume,
                        why="grow-back drain" if grow_drain
                        else "preemption",
                    )
                    if resume is not None:
                        config = dataclasses.replace(
                            config, resume_from_checkpoint=resume
                        )
                # Retry ONLY process death (≙ preemption/OOM).  A Python
                # exception in user code (RemoteError) is deterministic —
                # respawning would retrain epochs just to re-raise it.
                except ActorDiedError as err:
                    self._capture_attempt_events()
                    # A death supersedes any in-flight grow drain (the
                    # restart below is itself a grow opportunity); a
                    # stale flag would mislabel the NEXT preemption as
                    # a grow-back drain.
                    self._grow_pending = False
                    if not elastic:
                        raise
                    t_recover = time.monotonic()
                    target, rejected = self._elastic_resize_decision()
                    if rejected:
                        self._record_resize_rejected(target)
                        err.enrich(note=(
                            f"fleet capacity {target} below "
                            f"elastic_min_workers="
                            f"{self.elastic_min_workers} — shrink "
                            "rejected, restart abandoned"
                        ))
                        raise
                    if (target is not None
                            and target < self.active_workers):
                        # Capacity loss EXPLAINS the death: a preempted
                        # host is fleet churn, not a failure — respawn
                        # with the M survivors budget-free (like
                        # preempt_restarts), flap-guarded by forward
                        # progress of the resume point below.  Kill the
                        # doomed set FIRST: the dead rank's peers may be
                        # wedged inside the collective boundary, and
                        # discovery asking a wedged worker 0 would wait
                        # out its entire rendezvous timeout.
                        self._kill_workers(why="elastic-shrink")
                        info = self._discover_resume(config)
                        resume = info["path"]
                        self._resize_streak = (
                            self._resize_streak + 1
                            if resume == self._last_resize_ckpt else 0
                        )
                        self._last_resize_ckpt = resume
                        if self._resize_streak >= 2:
                            err.enrich(note=(
                                "no forward progress across "
                                "consecutive elastic resizes — flap "
                                "guard stopped the shrink loop"
                            ))
                            raise
                        warnings.warn(
                            f"Worker loss with reduced fleet capacity "
                            f"({err}); elastic shrink to {target} "
                            f"survivors (budget untouched), resuming "
                            f"from {resume or 'scratch'}."
                        )
                        self._respawn_resized(
                            target, t_recover, resume,
                            why="capacity loss",
                        )
                        if resume is not None:
                            config = dataclasses.replace(
                                config, resume_from_checkpoint=resume
                            )
                        continue
                    now = time.monotonic()
                    fail_times[:] = [
                        t for t in fail_times
                        if now - t <= self.restart_window_s
                    ]
                    if len(fail_times) >= self.max_restarts:
                        err.enrich(note=(
                            f"restart budget exhausted: "
                            f"{self.max_restarts} failure(s) within "
                            f"{self.restart_window_s:.0f}s"
                        ))
                        raise
                    fail_times.append(now)
                    self.restarts_used += 1
                    # Backoff exponent = failures currently IN the
                    # window (same clock as the budget): two deaths a
                    # day apart each wait the base delay; a flapping
                    # host doubles up within its hour.
                    fail_streak = len(fail_times)
                    delay = self._backoff_delay(fail_streak)
                    if delay > 0:
                        self._record_recovery(
                            "backoff", delay_s=round(delay, 3),
                            attempt=fail_streak,
                            message=(
                                f"waiting {delay:.2f}s before respawn "
                                f"#{fail_streak} (exponential backoff "
                                f"with jitter)"
                            ),
                        )
                        time.sleep(delay)
                    t_recover = time.monotonic()
                    # A restart is also a grow OPPORTUNITY: capacity
                    # that returned while running shrunk re-expands
                    # here without a deliberate grow drain.  The resize
                    # event is booked AFTER discovery so its
                    # recover_s/ckpt reflect the real detour.
                    old_active = self.active_workers
                    grew = target is not None and target != old_active
                    if grew:
                        self.active_workers = int(target)
                    self._respawn_workers()
                    info = self._discover_resume(config)
                    resume = info["path"]
                    if grew:
                        self._record_resize(
                            old_active, int(target), t_recover, resume,
                            why="restart",
                        )
                    self._record_recovery(
                        "elastic_restart", attempt=fail_streak,
                        recover_s=round(time.monotonic() - t_recover, 3),
                        ckpt=resume or "",
                        message=(
                            f"worker failure; elastic restart "
                            f"{len(fail_times)}/{self.max_restarts} in "
                            f"window, resuming from "
                            f"{resume or 'scratch'}"
                        ),
                    )
                    warnings.warn(
                        f"Worker failure ({err}); elastic restart "
                        f"{len(fail_times)}/{self.max_restarts} (window "
                        f"{self.restart_window_s:.0f}s), resuming from "
                        f"{resume or 'scratch'}."
                    )
                    if resume is not None:
                        config = dataclasses.replace(
                            config, resume_from_checkpoint=resume
                        )
        finally:
            if drain_installed:
                drain_mod.uninstall_signal_handlers()
            if kind == "fit":
                drain_mod.set_fit_active(False)
            # The scratch dir is uuid-named and unreachable for manual
            # resume; reclaim it on failure too, not just success —
            # EXCEPT when a raised PreemptedError names a drain
            # checkpoint inside it (the resumable exit's whole value).
            if restart_dir is not None and not preserve_scratch:
                shutil.rmtree(restart_dir, ignore_errors=True)

    def _latest_restart_checkpoint(self, restart_dir) -> Dict[str, Any]:
        """Newest VERIFIED restart/drain checkpoint, looked up ON WORKER
        0's node — the writer's filesystem (restart_dir must be shared
        storage for multi-node elastic recovery, the same assumption the
        reference makes for ModelCheckpoint files, ``ray_ddp.py:
        496-499``).  Falls back to a driver-local scan (valid on shared
        storage and the single-host backend) when worker 0 cannot
        answer."""
        if restart_dir is None:
            return {"path": None, "corrupt": []}
        if self._workers:
            try:
                return self._workers[0].execute(
                    _remote_latest_restart_checkpoint, restart_dir
                )
            except (ActorDiedError, RemoteError):
                pass
        return _remote_latest_restart_checkpoint(restart_dir)

    def _discover_resume(self, config: FitConfig) -> Dict[str, Any]:
        """Restart discovery + the ``ckpt_corrupt`` telemetry promise:
        every checkpoint the walk-back skipped becomes a loud event (and
        a warning) — silent fallback would hide data-eating storage."""
        info = self._latest_restart_checkpoint(config.restart_dir)
        for item in info.get("corrupt", []):
            problems = "; ".join(str(p) for p in item.get("problems", []))
            self._record_recovery(
                "ckpt_corrupt", ckpt=item.get("path", ""),
                message=(
                    f"checkpoint failed verification, falling back to "
                    f"an older one: {problems}"
                ),
            )
            warnings.warn(
                f"corrupt restart checkpoint skipped: "
                f"{item.get('path')} ({problems})"
            )
        return info

    # -- recovery bookkeeping ------------------------------------------------
    def _record_recovery(self, kind: str, **fields: Any) -> None:
        """A schema-shaped recovery event, kept on the strategy AND
        seeded into the next attempt's RunMonitor, so the final
        ``trainer.monitor_report`` narrates the whole fit across
        respawns (backoff delays included — the acceptance criterion)."""
        from ray_lightning_tpu.telemetry.monitor import make_event

        ev = make_event(kind, -1, **fields)
        self.recovery_events.append(ev)
        self._carried_events.append(ev)

    def _capture_attempt_events(self) -> None:
        """Fold the failed attempt's monitor record (stalls, dumps,
        aborts, crashes) into the carried history so the NEXT attempt's
        monitor — and thus the final report — keeps it."""
        if self._last_monitor is not None:
            self._carried_events = list(self._last_monitor.events)
            self._last_monitor = None

    def _backoff_delay(self, streak: int) -> float:
        """Exponential backoff with jitter: base × 2^(streak-1), capped,
        plus up to +25% jitter so a correlated fleet outage doesn't
        respawn every strategy in lockstep."""
        if self.restart_backoff_s <= 0:
            return 0.0
        base = min(
            self.restart_backoff_s * (2 ** max(streak - 1, 0)),
            self.restart_backoff_max_s,
        )
        return base * (1.0 + 0.25 * random.random())

    # -- elastic world sizing (shrink/grow governance) -----------------------
    def _fleet_capacity(self) -> int:
        """Workers the fleet can currently host.  Production installs
        pass ``elastic_capacity_fn`` (a fleet-API probe); the default
        reads the chaos plane's lost-worker markers
        (``fault.inject.lost_worker_count``) so a ``lose_worker@...``
        fault drives the shrink/grow path deterministically."""
        if self.elastic_capacity_fn is not None:
            return int(self.elastic_capacity_fn())
        from ray_lightning_tpu.fault import inject

        return self.num_workers - inject.lost_worker_count()

    def _elastic_resize_decision(self):
        """``(target_world, rejected)``: the size the next attempt
        should run at.  ``target_world`` is ``None`` when elastic
        sizing is off (``elastic_min_workers`` unset — fixed-size
        governance, the pre-elastic behavior); ``rejected`` flags
        capacity below the floor (the caller raises instead of
        training a crippled fleet)."""
        if self.elastic_min_workers is None:
            return None, False
        target = max(min(self._fleet_capacity(), self.num_workers), 0)
        if target < self.elastic_min_workers:
            return target, True
        return target, False

    def _record_resize_rejected(self, target: int) -> None:
        self._record_recovery(
            "resize_rejected",
            old_world=self.active_workers, new_world=target,
            message=(
                f"fleet capacity {target} below elastic_min_workers="
                f"{self.elastic_min_workers}; shrink rejected"
            ),
        )

    def _respawn_resized(self, target: Optional[int], t_recover: float,
                         resume: Optional[str], why: str) -> None:
        """Respawn the worker set, applying an elastic resize when
        ``target`` differs from the active size."""
        old = self.active_workers
        changed = target is not None and target != old
        if changed:
            self.active_workers = int(target)
        self._respawn_workers()
        if changed:
            self._record_resize(old, int(target), t_recover, resume, why)

    def _record_resize(self, old: int, new: int, t_recover: float,
                       resume: Optional[str], why: str) -> None:
        """Book one applied resize: the ``resize`` event (old/new world
        + recover_s) flows through the schema gate into
        ``trainer.monitor_report`` / OpenMetrics / ``rlt_top``, and any
        gang packer holding this trial's sub-mesh is notified so the
        freed devices can host other trials."""
        recover_s = round(time.monotonic() - t_recover, 3)
        self.resizes_used += 1
        self.last_resize_recover_s = recover_s
        self._record_recovery(
            "resize", old_world=old, new_world=new,
            recover_s=recover_s, ckpt=resume or "",
            message=(
                f"elastic resize: world {old} → {new} ({why}); "
                f"recovered in {recover_s}s"
            ),
        )
        warnings.warn(
            f"elastic resize: world {old} → {new} ({why})"
        )
        self._notify_packer_resize(old, new)

    def _notify_packer_resize(self, old: int, new: int) -> None:
        """Gang-packing hook: a trial running inside ``tune_run``'s
        fleet packer frees (or reclaims) sub-mesh devices when its
        governor resizes — best-effort, never costs the restart."""
        try:
            from ray_lightning_tpu.tuning import session as trial_session

            trial_session.notify_world_resize(old, new)
        except Exception as e:  # noqa: BLE001 - observer only
            log.debug("gang-packer resize notify failed: %r", e)

    def _maybe_request_grow(self) -> None:
        """Grow-back arming, run from the result-pump tick: when the
        fit runs below ``num_workers`` and capacity has been back for
        ``elastic_grow_after_s``, request a fleet drain — the resulting
        ``PreemptedError`` respawns budget-free at the larger size from
        the step-granular drain checkpoint."""
        if (self.elastic_min_workers is None
                or self.elastic_grow_after_s is None
                or self._grow_pending
                or self.active_workers >= self.num_workers):
            return
        cap = min(self._fleet_capacity(), self.num_workers)
        now = time.monotonic()
        if cap <= self.active_workers:
            self._capacity_ok_since = None
            return
        if self._capacity_ok_since is None:
            self._capacity_ok_since = now
            return
        if now - self._capacity_ok_since < self.elastic_grow_after_s:
            return
        self._grow_pending = True
        self._capacity_ok_since = None
        warnings.warn(
            f"fleet capacity returned ({cap} > {self.active_workers} "
            "active); draining to grow the worker set back"
        )
        delivered = 0
        for rank, w in enumerate(self._workers):
            request = getattr(w, "request_drain", None)
            if request is None:
                continue
            try:
                request(wait=False)
                delivered += 1
            except Exception as e:  # noqa: BLE001 - a dead worker
                # surfaces through the pump anyway
                log.debug("grow drain to rank %d failed: %r", rank, e)
        if delivered == 0:
            # Nobody heard the drain (backend without the control lane,
            # or every worker mid-death): a pending flag with no drain
            # coming would disarm grow-back for the rest of the fit.
            self._grow_pending = False

    def _maybe_broadcast_drain(self) -> None:
        """Driver-side preemption fan-out: the signal handler only sets
        a flag (no I/O in handlers); the pump tick turns it into one
        control-lane drain request per worker, fire-and-forget.

        RE-SENT every couple of seconds while the drain is pending: a
        worker still inside fit setup when the first request lands
        clears its process-wide flag at ``run_fit`` start (the inline-
        reuse reset), so a one-shot broadcast could be silently
        swallowed and the fleet would train through its grace window.
        ``request_drain`` is idempotent worker-side, so repeats are
        free."""
        if not drain_mod.drain_requested():
            return
        now = time.monotonic()
        if (self._drain_broadcast
                and now - self._drain_broadcast_at < 2.0):
            return
        if not self._drain_broadcast:
            warnings.warn(
                "drain requested on the driver — forwarding to workers"
            )
        self._drain_broadcast = True
        self._drain_broadcast_at = now
        for rank, w in enumerate(self._workers):
            request = getattr(w, "request_drain", None)
            if request is None:
                continue
            try:
                request(wait=False)
            except Exception as e:  # noqa: BLE001 - a dead worker can't
                # drain; its death surfaces through the pump anyway.
                log.debug(
                    "drain forward to rank %d failed: %r", rank, e
                )

    def _run_once(
        self,
        kind: str,
        module,
        datamodule,
        config: FitConfig,
        callbacks: List,
        trainer=None,
        params_stream: Optional[bytes] = None,
        ckpt_path: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        coordinator = self._broker_coordinator()
        task = {
            "kind": kind,
            "module": module,
            "datamodule": datamodule,
            "config": config,
            "callbacks": callbacks,
            "world_size": self.active_workers,
            "coordinator": coordinator,
            "mesh_axes": self.mesh_axes,
            "mode": self.mode,
            "zero_stage": self.zero_stage,
            "grad_comm": self.grad_comm,
            "telemetry": self.telemetry,
            "params_stream": params_stream,
            "ckpt_path": ckpt_path,
        }
        # Serialize ONCE; each worker materializes its own copy
        # (≙ ray.put(model), ray_ddp.py:339-342).
        task_ref = self._backend.put(task)
        queue = self._backend.create_queue()
        monitor = self._build_monitor(kind, config, trainer)
        futures = []
        try:
            futures = [
                w.submit(_execute_remote, task_ref, rank, queue.handle)
                for rank, w in enumerate(self._workers)
            ]
            on_item = getattr(trainer, "_on_stream_item", None)

            def _tick() -> None:
                # Driver-preemption fan-out rides the pump (signal
                # handlers must not do socket I/O), then the elastic
                # grow-back arming, then the watchdog.
                if kind == "fit":
                    self._maybe_broadcast_drain()
                    self._maybe_request_grow()
                if monitor is not None:
                    monitor.tick()

            results = process_results(
                futures, queue, on_item=on_item,
                on_tick=(
                    _tick if (monitor is not None or kind == "fit")
                    else None
                ),
            )
        except (ActorDiedError, RemoteError) as err:
            self._enrich_failure(err, futures, monitor)
            raise
        finally:
            if monitor is not None:
                monitor.finalize()
                adopt = getattr(trainer, "_adopt_monitor", None)
                if adopt is not None:
                    adopt(monitor)
            queue.shutdown()
            # Segment-backed task payloads are per-fit; without this,
            # repeated fits on one backend (PBT) leak tmpfs ∝ fits × size.
            task_ref.release()
        return results

    # -- live monitoring (telemetry/monitor.py) -----------------------------
    def _build_monitor(self, kind: str, config: FitConfig, trainer):
        """A RunMonitor for fit stages at enabled telemetry tiers —
        ``telemetry="off"`` installs no monitor at all."""
        if kind != "fit":
            return None
        from ray_lightning_tpu.telemetry import (
            MonitorConfig,
            RunMonitor,
            TelemetryConfig,
        )

        tel_cfg = TelemetryConfig.coerce(self.telemetry)
        if tel_cfg.tier == "off" or tel_cfg.heartbeat_s <= 0:
            return None
        mon_cfg = MonitorConfig.coerce(
            self.monitor, heartbeat_s=tel_cfg.heartbeat_s
        )
        if mon_cfg.out_dir is None:
            mon_cfg = dataclasses.replace(
                mon_cfg,
                out_dir=tel_cfg.export_dir or os.path.join(
                    config.default_root_dir, "telemetry"
                ),
            )
        monitor = RunMonitor(
            mon_cfg,
            world_size=self.active_workers,
            dump_cb=self._dump_rank_stacks,
            abort_cb=self._abort_workers,
        )
        # Seed the attempt's monitor with the recovery history so far
        # (previous attempts' stalls/aborts/crashes + the strategy's
        # backoff/restart/ckpt_corrupt events): the LAST adopted report
        # is what lands in trainer.monitor_report, and it must narrate
        # the whole fit, not just the surviving attempt.
        for ev in self._carried_events:
            monitor._record_event(ev)
        self._last_monitor = monitor
        attach = getattr(trainer, "_attach_monitor", None)
        if attach is not None:
            attach(monitor)
        return monitor

    def _dump_rank_stacks(self, rank: int):
        """Monitor dump hook: out-of-band py-stack + device-memory dump
        of one worker (served mid-call via the actor control lane).
        Backends whose workers lack the lane (the Ray adapter) degrade
        to a clear error event instead of a puzzling AttributeError."""
        worker = self._workers[rank]
        dump = getattr(worker, "dump_stacks", None)
        if dump is None:
            raise RuntimeError(
                f"{type(worker).__name__} has no control lane — "
                "out-of-band stack dumps need ProcessActor workers "
                "(use Ray's py-spy tooling on Ray clusters)"
            )
        return dump()

    def _abort_workers(self, reason: str) -> None:
        """Monitor abort hook: kill the worker set so the pump's futures
        fail instead of waiting on a hung collective forever.  With
        ``max_restarts`` set, the resulting ActorDiedError feeds the
        ELASTIC path — a wedged collective becomes a restart, not a
        dead fit."""
        warnings.warn(f"RunMonitor abort: {reason} — killing workers")
        self._kill_workers(timeout=1.0, why="monitor-abort")

    def _enrich_failure(self, err, futures, monitor) -> None:
        """Make a worker-death report say when/how the rank died: rank
        (from the failed future), exit code (agent/subprocess poll),
        last-heartbeat age and flight-bundle paths (from the monitor)."""
        rank = next(
            (
                i for i, f in enumerate(futures)
                if f.done() and f.exception() is err
            ),
            None,
        )
        bundles = monitor.crash_bundles() if monitor is not None else []
        notes = []
        if bundles:
            notes.append("flight bundle(s): " + ", ".join(bundles))
        # A death DURING the drain window must say a drain checkpoint
        # exists and where — the operator's next move is resuming from
        # it, not spelunking the scratch dir (mirrors how crash errors
        # name their flight bundles).
        drains = (
            monitor.drain_checkpoints() if monitor is not None else []
        )
        if drains:
            notes.append("drain checkpoint(s): " + ", ".join(drains))
        note = "; ".join(notes) or None
        if isinstance(err, ActorDiedError):
            fields = {"note": note} if note else {}
            if monitor is not None and monitor.abort_reason:
                fields["note"] = "; ".join(filter(None, [
                    note, f"aborted by RunMonitor: {monitor.abort_reason}"
                ]))
            if rank is not None:
                fields["rank"] = rank
                if rank < len(self._workers):
                    worker = self._workers[rank]
                    fields["exit_code"] = worker._proc.poll()
                if monitor is not None:
                    fields["last_heartbeat_age_s"] = (
                        monitor.last_heartbeat_age_s(rank)
                    )
            if fields:
                err.enrich(**fields)
        elif note:
            # RemoteError: the bundle path must still be in the message
            # a user reads first.
            err.args = (f"{err.args[0]}\n[{note}]",) + err.args[1:]

    def teardown(self) -> None:
        """Kill workers (≙ ``post_dispatch`` teardown, ``ray_ddp.py:398-401``)."""
        if self._backend is not None:
            if getattr(self, "_owns_backend", True):
                self._backend.shutdown()
            else:
                self._kill_workers(why="teardown")
        self._workers = []
        self._backend = None

    # -- introspection -------------------------------------------------------
    def get_worker_device_info(self) -> List[Dict[str, Any]]:
        """Device topology of every worker (rank/mesh mapping input;
        ≙ ``get_node_and_gpu_ids`` sweep at ``ray_ddp.py:230-274``)."""
        return [w.get_device_info() for w in self._workers]

    def get_worker_host_stats(self) -> List[Dict[str, Any]]:
        """Per-worker host load/memory — the straggler-context companion
        to ``trainer.telemetry_report``'s rank-skew view."""
        return [w.get_host_stats() for w in self._workers]


class LocalStrategy(TpuStrategy):
    """In-process execution on the driver's own devices (no actors).

    The analogue of running Lightning without any Ray plugin; used for
    single-host TPU runs (bench) and as ``Trainer()``'s default.  Still
    builds a mesh over the local devices, so data parallelism across the
    chips of one host works identically.
    """

    def __init__(self, mesh_axes: Optional[Dict[str, int]] = None,
                 mode: str = "gspmd", zero_stage: int = 0,
                 grad_comm=None, telemetry=None, monitor=None,
                 megastep=None, update_sharding=None,
                 grad_overlap_segments=None):
        super().__init__(
            num_workers=1, mesh_axes=mesh_axes, grad_comm=grad_comm,
            telemetry=telemetry, monitor=monitor, megastep=megastep,
            update_sharding=update_sharding,
            grad_overlap_segments=grad_overlap_segments,
        )
        if monitor is not None:
            warnings.warn(
                "monitor= has no effect on LocalStrategy: the RunMonitor "
                "rides the driver's result pump, which inline fits never "
                "enter.  Local fits still stream heartbeats to "
                "<root>/telemetry/heartbeats-rank0.jsonl (rlt_top reads "
                "them); use a remote strategy for watchdog/abort."
            )
        self.mode = mode
        self.zero_stage = zero_stage

    @property
    def is_distributed(self) -> bool:
        return False

    def setup(self, trainer) -> None:
        if self.init_hook is not None:
            self.init_hook()

    def run(
        self,
        kind: str,
        module,
        datamodule,
        config: FitConfig,
        callbacks: List,
        trainer=None,
        params_stream: Optional[bytes] = None,
        ckpt_path: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh

        if config.megastep is None and self.megastep is not None:
            config = dataclasses.replace(config, megastep=self.megastep)
        if (config.update_sharding is None
                and self.update_sharding is not None):
            config = dataclasses.replace(
                config, update_sharding=self.update_sharding
            )
        if (config.grad_overlap_segments is None
                and self.grad_overlap_segments is not None):
            config = dataclasses.replace(
                config, grad_overlap_segments=self.grad_overlap_segments
            )
        # Gang-packing: inside a tune_run trial holding a sub-mesh
        # allocation (tuning/pack.py), build the mesh over exactly the
        # allocated devices — concurrent trials then run on DISJOINT
        # slices of one fleet instead of time-sharing every chip.
        devices = None
        try:
            from ray_lightning_tpu.tuning.session import (
                current_trial_devices,
            )

            indices = current_trial_devices()
        except Exception:  # noqa: BLE001 - tuner not in play
            indices = None
        if indices:
            import jax

            all_devices = jax.devices()
            bad = [i for i in indices if not 0 <= i < len(all_devices)]
            if bad:
                raise ValueError(
                    f"trial sub-mesh allocation names device indices "
                    f"{bad} but only {len(all_devices)} devices exist — "
                    "fleet_devices must not exceed the host's device "
                    "count for LocalStrategy trials"
                )
            devices = [all_devices[i] for i in indices]
        mesh = build_mesh(MeshSpec(self.mesh_axes), devices=devices)
        common = dict(
            module=module, datamodule=datamodule, config=config,
            global_rank=0, world_size=1, mesh=mesh,
        )
        if kind == "fit":
            try:
                return [run_fit(callbacks=callbacks, mode=self.mode,
                                zero_stage=self.zero_stage,
                                grad_comm=self.grad_comm,
                                telemetry=self.telemetry, **common)]
            except PreemptedError:
                # An inline drain is an orderly exit with its checkpoint
                # already written and named — not a crash to record.
                raise
            except BaseException as err:
                # Inline fits get the same crash forensics as remote
                # workers; there is no queue, so name the bundle loudly
                # here instead of on a stream event.
                from ray_lightning_tpu.telemetry.flight_recorder import (
                    record_active_crash,
                )

                bundle = record_active_crash(err)
                if bundle is not None:
                    warnings.warn(f"crash flight bundle written: {bundle}")
                raise
        if kind in ("validation", "test"):
            return [run_eval(callbacks=callbacks, kind=kind, mode=self.mode,
                             zero_stage=self.zero_stage,
                             params_stream=params_stream,
                             ckpt_path=ckpt_path,
                             telemetry=self.telemetry, **common)]
        if kind == "predict":
            return [run_predict(zero_stage=self.zero_stage,
                                params_stream=params_stream,
                                ckpt_path=ckpt_path,
                                telemetry=self.telemetry, **common)]
        raise ValueError(f"Unknown stage kind {kind!r}")

    def teardown(self) -> None:
        pass


class RayStrategy(TpuStrategy):
    """Data-parallel strategy over worker actors (≙ ``RayPlugin``).

    GSPMD flavor: the jitted train step sees the global batch sharded over
    the ``data`` mesh axis; XLA compiles the gradient all-reduce into the
    program and overlaps it with backward compute on ICI — the TPU-native
    equivalent of DDP's bucketed NCCL all-reduce.
    """

    mode = "gspmd"
    zero_stage = 0


class HorovodRayStrategy(TpuStrategy):
    """Explicit-collective flavor (≙ ``HorovodRayPlugin``).

    Per-device SPMD via ``shard_map``: each device computes gradients on
    its batch shard and calls ``lax.pmean`` over the data axis — the same
    ring all-reduce Horovod runs, but compiler-scheduled over ICI.
    """

    mode = "shard_map"
    zero_stage = 0


class RayShardedStrategy(TpuStrategy):
    """ZeRO-sharded data parallel (≙ ``RayShardedPlugin``/FairScale OSS).

    ``zero_stage=1`` shards optimizer state (OSS); ``zero_stage=3`` also
    shards parameters (FSDP-style).  Implemented purely as NamedSharding
    annotations on the train state — no wrapper classes
    (SURVEY §7: "sharding is an annotation").

    ``zero_stage=2`` ("shard gradients too", FairScale SDP /
    ``ray_ddp_sharded.py:17-34``) is accepted for compatibility but
    **normalized to stage 1 with a warning**: under GSPMD, gradients are
    transient values inside one jitted step — they are never materialized
    as persistent per-rank state, and XLA already reduce-scatters them
    where profitable — so there is nothing extra to annotate and no
    distinct stage-2 memory behavior to select.  A benchmark labeled
    stage 2 would measure exactly stage 1; the normalization keeps users
    from misreporting what they ran.
    """

    mode = "gspmd"

    def __init__(self, *args, zero_stage: int = 1, **kwargs):
        super().__init__(*args, **kwargs)
        if zero_stage not in (1, 2, 3):
            raise ValueError("zero_stage must be 1, 2 or 3")
        if zero_stage == 2:
            import warnings

            warnings.warn(
                "zero_stage=2 is equivalent to zero_stage=1 on this "
                "framework (GSPMD gradients are transient inside the "
                "jitted step; XLA reduce-scatters them automatically). "
                "Normalizing to zero_stage=1 — pass 1 or 3 explicitly "
                "to silence this warning."
            )
            zero_stage = 1
        self.zero_stage = zero_stage


class MpmdStrategy(TpuStrategy):
    """MPMD pipeline parallelism: one actor per pipeline stage, each
    with its OWN mesh and separately compiled programs (mesh-of-meshes,
    the JaxPP shape — docs/ARCHITECTURE.md round 12).

    Unlike the SPMD strategies there is no shared jitted program and no
    ``jax.distributed`` world: stage workers exchange activations and
    activation-gradients over the :mod:`~ray_lightning_tpu.mpmd.transfer`
    lane (shared-memory segments same-host, TCP queues across DCN) and
    follow explicit per-worker instruction streams
    (:mod:`~ray_lightning_tpu.mpmd.schedule`).

    Knobs: ``num_stages`` (= worker actors), ``schedule`` ("gpipe" |
    "1f1b"), ``num_microbatches``, ``interleave`` (model chunks per
    worker — the 1F1B-interleaved bubble shrink), ``devices_per_stage``
    (CPU simulation: virtual device count per stage actor),
    ``ckpt_every_n_steps`` (per-stage restart checkpoints — the
    restart governor resumes at the newest step EVERY stage persisted).

    The elastic machinery is inherited: a dead stage actor raises
    ``ActorDiedError`` into the same sliding-window restart governor,
    and a drain request makes every stage write a step-exact drain
    checkpoint and exit with ``PreemptedError``.

    Fit-only: eval/predict have no pipeline formulation here yet (run
    them through an SPMD strategy on the reassembled params).
    """

    mode = "mpmd"
    supports_elastic_resize = False  # the stage count is structural

    def __init__(
        self,
        num_stages: int = 2,
        schedule: str = "1f1b",
        num_microbatches: int = 8,
        interleave: int = 1,
        devices_per_stage: Optional[int] = None,
        recv_timeout_s: float = 120.0,
        ckpt_every_n_steps: int = 1,
        tx_factory: Optional[Callable[[], Any]] = None,
        trace_dir: Optional[str] = None,
        wire_dtype: Any = None,
        **kwargs: Any,
    ):
        from ray_lightning_tpu.mpmd.schedule import SCHEDULES
        from ray_lightning_tpu.mpmd.transfer import WireDtypeConfig

        if wire_dtype is not None:
            # Eager validation (a bad codec string must fail at
            # construction, not inside a stage actor); the validated
            # value still ships as the raw knob so workers re-coerce —
            # None defers to the bridged RLT_MPMD_WIRE_DTYPE env knob.
            WireDtypeConfig.coerce(wire_dtype)

        if num_stages < 1:
            raise ValueError("num_stages must be >= 1")
        if schedule not in SCHEDULES:
            raise ValueError(
                f"unknown schedule {schedule!r} (expected one of "
                f"{SCHEDULES})"
            )
        if interleave < 1:
            raise ValueError("interleave must be >= 1")
        if interleave > 1 and schedule != "1f1b":
            raise ValueError(
                "interleave > 1 requires schedule='1f1b' (interleaved "
                "GPipe would deepen the pipe without shrinking the "
                "bubble)"
            )
        if interleave > 1 and num_stages < 2:
            raise ValueError(
                "interleave > 1 needs num_stages >= 2: a single worker "
                "has no pipeline to overlap, and its chunk handoffs "
                "would need a self-loop transfer lane the actor plane "
                "does not wire"
            )
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        if ckpt_every_n_steps < 1:
            raise ValueError("ckpt_every_n_steps must be >= 1")
        if kwargs.get("elastic_min_workers") is not None:
            raise ValueError(
                "MpmdStrategy cannot resize elastically: the stage "
                "count is structural (the layer split is baked into "
                "every stage's compiled program); run SPMD strategies "
                "for shrink/grow recovery"
            )
        kwargs.setdefault("use_tpu", devices_per_stage is None)
        # supports_elastic_resize = False (class attr below): the
        # fleet-wide RLT_ELASTIC_* env bus is ignored here for the same
        # structural reason, rather than crashing pipeline fits.
        super().__init__(num_workers=num_stages, **kwargs)
        self.schedule = schedule
        self.num_microbatches = num_microbatches
        self.interleave = interleave
        self.devices_per_stage = devices_per_stage
        self.recv_timeout_s = recv_timeout_s
        self.ckpt_every_n_steps = ckpt_every_n_steps
        self.tx_factory = tx_factory
        self.wire_dtype = wire_dtype
        # Distributed step tracing (docs/OBSERVABILITY.md): a SHARED
        # path (same-host fleets or a shared mount) each stage actor
        # exports trace-mpmd-stage<k>.jsonl into at fit end; None =
        # tracing off, nothing installed.
        self.trace_dir = trace_dir
        # Post-fit pipeline report (schedule, per-stage occupancy, the
        # measured-cost bubble decomposition) — the mpmd analogue of
        # trainer.telemetry_report.
        self.mpmd_report: Dict[str, Any] = {}
        self._live_stage_items: Dict[int, Dict[str, Any]] = {}
        self._live_written_at = 0.0
        self._live_dir: Optional[str] = None
        if devices_per_stage is not None:
            # CPU-simulated stage meshes: each stage ACTOR gets its own
            # virtual device count (its private "mesh"), replacing any
            # inherited test-harness value.
            import re as _re

            flags = os.environ.get("XLA_FLAGS", "")
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", "", flags
            ).strip()
            self.env_per_worker.setdefault(
                "XLA_FLAGS",
                (f"{flags} --xla_force_host_platform_device_count="
                 f"{devices_per_stage}").strip(),
            )

    # The live monitor rides run_fit's heartbeat publisher, which stage
    # workers do not run — the mpmd_stage stream is their live plane.
    def _build_monitor(self, kind, config, trainer):
        return None

    def run(self, kind, module, datamodule, config, callbacks,
            trainer=None, params_stream=None, ckpt_path=None):
        if kind != "fit":
            raise NotImplementedError(
                "MpmdStrategy supports fit only; run validate/test/"
                "predict through an SPMD strategy on the trained params"
            )
        return super().run(
            kind, module, datamodule, config, callbacks, trainer=trainer,
            params_stream=params_stream, ckpt_path=ckpt_path,
        )

    def _latest_restart_checkpoint(self, restart_dir) -> Dict[str, Any]:
        from ray_lightning_tpu.mpmd.worker import latest_mpmd_checkpoint

        return latest_mpmd_checkpoint(restart_dir, self.num_workers)

    # -- live export ---------------------------------------------------------
    def _on_mpmd_item(self, item: Any) -> None:
        if not (isinstance(item, dict)
                and item.get("type") == "mpmd_stage"):
            return
        self._live_stage_items[int(item.get("stage", -1))] = item
        now = time.monotonic()
        if self._live_dir is None or now - self._live_written_at < 0.5:
            return
        self._live_written_at = now
        self._write_live_snapshot()

    def _live_snapshot(self) -> Dict[str, Any]:
        stages = [
            self._live_stage_items[k]
            for k in sorted(self._live_stage_items)
        ]
        return {
            "ts": time.time(),
            "mpmd": {
                "schedule": self.schedule,
                "interleave": self.interleave,
                "n_micro": self.num_microbatches,
                "n_stages": self.num_workers,
                "stages": stages,
            },
        }

    def _write_live_snapshot(self) -> None:
        import json

        if self._live_dir is None:
            return
        try:
            os.makedirs(self._live_dir, exist_ok=True)
            path = os.path.join(self._live_dir, "mpmd-live.json")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self._live_snapshot(), f)
            os.replace(tmp, path)
        except OSError as e:
            log.debug("mpmd live snapshot write failed: %r", e)

    def _run_once(
        self,
        kind: str,
        module,
        datamodule,
        config: FitConfig,
        callbacks: List,
        trainer=None,
        params_stream: Optional[bytes] = None,
        ckpt_path: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        import numpy as np

        from ray_lightning_tpu.mpmd import worker as mpmd_worker
        from ray_lightning_tpu.mpmd.plan import (
            StagePlan,
            resolve_mpmd_spec,
        )
        from ray_lightning_tpu.mpmd.schedule import (
            fleet_pipeline_stats,
            measured_schedule_bubble,
            pool_op_costs,
        )

        spec = resolve_mpmd_spec(module)  # fail fast, driver-side
        plan = StagePlan.split(
            spec.n_layers, self.num_workers * self.interleave
        )
        self._live_stage_items = {}
        self._live_dir = os.path.join(
            config.default_root_dir, "telemetry"
        )

        is_local = isinstance(self._backend, backend_mod.LocalBackend)
        addrs = [
            w.execute(mpmd_worker._remote_create_inbox, is_local)
            for w in self._workers
        ]
        task = {
            "module": module,
            "datamodule": datamodule,
            "config": config,
            "n_workers": self.num_workers,
            "interleave": self.interleave,
            "n_micro": self.num_microbatches,
            "schedule": self.schedule,
            "mesh_axes": self.mesh_axes,
            "same_host": is_local,
            "recv_timeout_s": self.recv_timeout_s,
            "restart_dir": config.restart_dir,
            "resume_prefix": config.resume_from_checkpoint,
            "ckpt_every": self.ckpt_every_n_steps,
            "steps": (
                config.max_steps if config.max_steps
                and config.max_steps > 0 else None
            ),
            "tx_factory": self.tx_factory,
            "trace_dir": self.trace_dir,
            "wire_dtype": self.wire_dtype,
        }
        task_ref = self._backend.put(task)
        queue = self._backend.create_queue()
        on_item_trainer = getattr(trainer, "_on_stream_item", None)

        def on_item(item):
            self._on_mpmd_item(item)
            if on_item_trainer is not None:
                on_item_trainer(item)

        def _tick() -> None:
            self._maybe_broadcast_drain()

        futures = []
        try:
            futures = [
                w.submit(
                    mpmd_worker._stage_execute_remote, task_ref, rank,
                    queue.handle,
                    addrs[(rank - 1) % self.num_workers]
                    if self.num_workers > 1 else None,
                    addrs[(rank + 1) % self.num_workers]
                    if self.num_workers > 1 else None,
                )
                for rank, w in enumerate(self._workers)
            ]
            results = process_results(
                futures, queue, on_item=on_item, on_tick=_tick
            )
        except RemoteError as err:
            # A dead stage wedges its PEERS' transfer lanes: a peer's
            # recv-timeout/send-failure can resolve BEFORE the driver
            # notices the death, surfacing as RemoteError — which would
            # bypass the restart governor.  If any worker is actually
            # dead, the death is the root cause: raise it as such.
            dead = next(
                (
                    rank for rank, w in enumerate(self._workers)
                    if not w.is_alive()
                ),
                None,
            )
            if dead is not None:
                raise ActorDiedError(
                    f"stage worker {dead} died mid-fit (peer error: "
                    f"{err.args[0].splitlines()[0] if err.args else err})",
                    rank=dead,
                ) from err
            self._enrich_failure(err, futures, None)
            raise
        except ActorDiedError as err:
            self._enrich_failure(err, futures, None)
            raise
        finally:
            queue.shutdown()
            task_ref.release()

        # -- assemble the rank-0-shaped result package -------------------
        results = sorted(results, key=lambda r: r["rank"])
        n_stages = plan.n_stages
        parts = [
            results[g % self.num_workers]["chunks"][g // self.num_workers]
            for g in range(n_stages)
        ]
        full_params = spec.assemble_params(parts, plan)
        loss_result = next(r for r in results if r.get("hosts_loss"))
        final_step = int(loss_result["final_step"])

        per_stage = [r["stats"] for r in results]
        costs = pool_op_costs([r["op_costs"] for r in results])
        report = {
            "schedule": self.schedule,
            "interleave": self.interleave,
            "n_stages": self.num_workers,
            "n_micro": self.num_microbatches,
            "steps": final_step,
            "losses": list(loss_result["losses"]),
            "per_stage": per_stage,
            "op_costs_ms": {
                k: v * 1e3 for k, v in costs.items()
            },
            **fleet_pipeline_stats(per_stage),
        }
        if costs:
            report["bubble_fraction"] = measured_schedule_bubble(
                self.schedule, self.num_workers, self.num_microbatches,
                self.interleave, costs,
            )
        xfers = [r["xfer"] for r in results if r.get("xfer")]
        if xfers:
            sent = sum(int(x.get("bytes_sent", 0)) for x in xfers)
            full = sum(int(x.get("bytes_full_width", 0)) for x in xfers)
            wire: Dict[str, Any] = {
                "bytes_sent": sent,
                "bytes_full_width": full,
                "wire_ratio": (full / sent) if sent else 1.0,
                "per_stage": xfers,
            }
            enc = next((x["enc"] for x in xfers if x.get("enc")), None)
            if enc is not None:
                wire["enc"] = enc
            report["xfer"] = wire
        self.mpmd_report = report
        self._write_live_snapshot()

        from ray_lightning_tpu.core.module import TrainState
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        state = TrainState(
            params=full_params,
            opt_state=None,  # per-stage moments stay with their stages
            step=np.int32(final_step),
        )
        metrics = dict(loss_result["callback_metrics"])
        metrics.update({
            "bubble_fraction": report.get("bubble_fraction", 0.0),
            "stage_occupancy": report["stage_occupancy"],
        })
        package = {
            "rank": 0,
            "state_stream": to_state_stream(state),
            "callback_metrics": metrics,
            "logged_metrics": dict(metrics),
            "best_model_path": "",
            "epochs_run": 1,
            "global_step": final_step,
            "micro_step": final_step * self.num_microbatches,
            "callback_states": [],
            "comm_stats": {},
            "telemetry": None,
        }
        return [package]


# Reference-name aliases (≙ ray_lightning's public exports, __init__.py:1-5)
RayPlugin = RayStrategy
HorovodRayPlugin = HorovodRayStrategy
RayShardedPlugin = RayShardedStrategy
