from .mesh import (
    MeshSpec,
    bootstrap_distributed,
    build_mesh,
    compute_host_ranks,
    partition_host_chips,
)
from .pipeline import pipeline_apply, pipelined_scan
from .sharding import (
    batch_sharding,
    make_global_batch,
    replicated,
    shard_leaf_spec,
    zero_state_shardings,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "bootstrap_distributed",
    "compute_host_ranks",
    "partition_host_chips",
    "pipeline_apply",
    "pipelined_scan",
    "batch_sharding",
    "make_global_batch",
    "replicated",
    "shard_leaf_spec",
    "zero_state_shardings",
    "TpuStrategy",
    "LocalStrategy",
    "RayStrategy",
    "HorovodRayStrategy",
    "RayShardedStrategy",
    "MpmdStrategy",
    "RayPlugin",
    "HorovodRayPlugin",
    "RayShardedPlugin",
]

_STRATEGY_NAMES = (
    "TpuStrategy",
    "LocalStrategy",
    "RayStrategy",
    "HorovodRayStrategy",
    "RayShardedStrategy",
    "MpmdStrategy",
    "RayPlugin",
    "HorovodRayPlugin",
    "RayShardedPlugin",
)


def __getattr__(name):
    # Lazy: strategies imports the core loop, which imports this package's
    # sharding module — an eager import here would be a cycle.
    if name in _STRATEGY_NAMES:
        from . import strategies

        return getattr(strategies, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
