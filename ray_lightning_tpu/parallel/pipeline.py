"""Pipeline parallelism (GPipe-style), TPU-first: SPMD over a ``pipe``
mesh axis with ``lax.ppermute`` stage handoffs.

Closes the one §2.3 gap (PP) — absent in the reference too (SURVEY: not
required for parity), so this is net-new capability.  The design follows
the scaling-book/praxis collective-permute pipelining recipe rather than
any torch-style stage-process model:

* **Layers are the stacked leading axis** (the same ``(L, ...)`` layout
  the GPT scan uses): sharding that axis over the ``pipe`` mesh axis IS
  the stage assignment — stage ``p`` holds layers
  ``[p*L/P, (p+1)*L/P)`` and runs them with the usual ``lax.scan``.
* **Software pipeline over microbatches**: at tick ``t`` stage ``p``
  works on microbatch ``t - p``; activations hop to the next stage via
  ``ppermute`` (compiler-scheduled over ICI).  ``M`` microbatches drain
  in ``M + P - 1`` ticks — the classic GPipe bubble of
  ``(P-1)/(M+P-1)``, amortized by choosing ``M >> P``.
* **Bubble slots are masked, not branched**: every stage executes the
  identical program every tick (SPMD — no data-dependent control flow
  under ``jit``); out-of-range microbatch slots simply produce garbage
  that no output slot ever selects.
* **Differentiable end-to-end**: the transpose of ``ppermute`` is the
  reverse ``ppermute``, so ``jax.grad`` of a pipelined loss is itself a
  (reverse) pipeline — backward stage handoffs come out of autodiff, no
  hand-written schedule.

``pipeline_apply`` is the generic primitive; ``tests/test_pipeline.py``
proves forward and gradient parity against the plain scan on dp×pp CPU
meshes, and ``__graft_entry__.dryrun_multichip`` exercises a pp flavor.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu.utils.jax_compat import pcast

__all__ = ["pipeline_apply", "pipelined_scan", "layer_splits"]


def layer_splits(
    n_layers: int, n_stages: int, *, require_divisible: bool = False
) -> tuple:
    """Contiguous stage boundaries over a stacked ``(L, ...)`` layer axis.

    Returns ``(b_0, ..., b_P)`` with stage ``p`` owning layers
    ``[b_p, b_{p+1})``.  The single source of the layer-axis split math:
    the SPMD GPipe flavor here requires an even split (the sharded axis
    is one leaf — ``require_divisible=True``), while the MPMD plane
    (:mod:`ray_lightning_tpu.mpmd`) slices per stage and balances a
    remainder onto the EARLIEST stages (front-loaded: stage 0 also owns
    the embedding prologue, but the alternative — a fat LAST stage —
    would stack the remainder on top of the loss/LM-head epilogue, the
    heavier end for LM shapes).
    """
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_layers < n_stages:
        raise ValueError(
            f"{n_layers} layers cannot fill {n_stages} pipeline stages "
            "(every stage needs at least one layer)"
        )
    if n_layers % n_stages:
        if require_divisible:
            raise ValueError(
                f"layer axis has {n_layers} layers, not divisible into "
                f"{n_stages} pipeline stages"
            )
    base, extra = divmod(n_layers, n_stages)
    bounds = [0]
    for p in range(n_stages):
        bounds.append(bounds[-1] + base + (1 if p < extra else 0))
    return tuple(bounds)


def pipelined_scan(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    local_params: Any,
    x_micro: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Per-device GPipe body — run inside ``shard_map`` with ``axis_name``
    mapped over the pipeline axis.

    Args:
        stage_fn: ``(local_params, x) -> x`` — applies THIS stage's layer
            stack to one microbatch of activations.
        local_params: the stage's parameter shard (layer axis already
            split by the ``shard_map`` in_specs).
        x_micro: ``(M, mb, ...)`` microbatched activations, replicated
            across the pipe axis (every stage sees the inputs; only
            stage 0 reads them).
        axis_name: the pipeline mesh axis.

    Returns:
        ``(M, mb, ...)`` outputs of the LAST stage, replicated back to
        every member of the pipe group (so downstream losses are
        pipe-replicated, keeping GSPMD layouts simple).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = x_micro.shape[0]
    ticks = m + n_stages - 1
    fwd_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    x_shape = x_micro.shape[1:]
    zeros = jnp.zeros(x_shape, x_micro.dtype)
    out0 = jnp.zeros_like(x_micro)

    def tick(carry, t):
        prev_out, outputs = carry
        # Activation arriving from the previous stage (stage 0 receives
        # the wrap-around garbage from the last stage and ignores it).
        arriving = jax.lax.ppermute(prev_out, axis_name, fwd_perm)
        # Stage 0 feeds itself from the microbatch stream while t < M
        # (afterwards it idles on a zero block during pipeline drain).
        feed_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(t < m, x_micro[feed_idx], zeros)
        x_in = jnp.where(stage == 0, fed, arriving)
        y = stage_fn(local_params, x_in)
        # The LAST stage completes microbatch t - (P-1) at tick t.
        done_idx = t - (n_stages - 1)
        take = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(take, y, outputs[jnp.clip(done_idx, 0, m - 1)]),
            jnp.clip(done_idx, 0, m - 1),
            axis=0,
        )
        return (y, outputs), None

    # Initial carries must hold the varying-manual-axes type the loop
    # body produces (same shard_map VMA discipline as ring_attention).
    init = (
        pcast(zeros, (axis_name,), to="varying"),
        pcast(out0, (axis_name,), to="varying"),
    )
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
    # Replicate the last stage's outputs across the pipe group: sum a
    # one-hot-by-stage contribution (every other stage contributes 0).
    mine = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(mine, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    num_microbatches: int | None = None,
) -> jax.Array:
    """Global-view wrapper: apply an ``(L, ...)``-stacked layer pytree to
    ``x (B, ...)`` as a ``P``-stage pipeline over ``mesh[pipe_axis]``.

    ``stage_fn(local_params, x)`` receives the ``(L/P, ...)`` local layer
    shard.  The batch is split into ``num_microbatches`` (default: one
    per stage — callers should raise it to shrink the bubble).
    """
    from ray_lightning_tpu.utils.jax_compat import shard_map

    n_stages = mesh.shape[pipe_axis]
    if num_microbatches is not None and num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got "
                         f"{num_microbatches}")
    m = num_microbatches if num_microbatches is not None else n_stages
    b = x.shape[0]
    if b % m:
        raise ValueError(
            f"batch {b} not divisible into {m} microbatches"
        )
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            stacked_params)[0]:
        try:
            layer_splits(leaf.shape[0], n_stages, require_divisible=True)
        except ValueError as err:
            raise ValueError(
                f"layer axis of {jax.tree_util.keystr(path)}: {err}"
            ) from None
    x_micro = x.reshape(m, b // m, *x.shape[1:])

    # Layer axis (leading) sharded over pipe; everything else replicated.
    param_spec = jax.tree_util.tree_map(
        lambda _: P(pipe_axis), stacked_params
    )
    fn = functools.partial(pipelined_scan, stage_fn, axis_name=pipe_axis)
    out = shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
    )(stacked_params, x_micro)
    return out.reshape(b, *out.shape[2:])
