"""Sharding rules: ZeRO-style state sharding as GSPMD annotations.

≙ the reference's FairScale OSS / ShardedDataParallel / ShardedGradScaler
stack (``/root/reference/ray_lightning/ray_ddp_sharded.py:17-34``), which
wraps the model and optimizer in sharding *classes*.  On TPU the same
capability is a **compiler annotation** (SURVEY §7: "sharding is an
annotation, not a wrapper class"): we compute a ``NamedSharding`` for every
leaf of the train state and hand it to ``jax.jit`` as in/out shardings —
XLA then keeps optimizer state (ZeRO-1) and optionally parameters (ZeRO-3
/ FSDP) partitioned across the mesh, inserting reduce-scatter/all-gather
collectives over ICI where needed.

Leaf rule: shard the **largest axis divisible by the mesh axis size**;
small leaves (biases, scalars, layernorm gains) stay replicated — the
standard weight-update-sharding recipe (cf. "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", arXiv:2004.13336).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "replicated",
    "host_replicated_copy",
    "batch_sharding",
    "data_axes",
    "default_zero_axis",
    "shard_leaf_spec",
    "zero_state_shardings",
    "state_shardings_for_module",
    "params_shardings_for_module",
    "make_global_batch",
    "stacked_batch_sharding",
    "stack_host_batches",
    "make_global_stacked_batch",
]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@functools.lru_cache(maxsize=8)
def _replicate_fn(mesh: Mesh):
    """Cached jitted identity with replicated out_shardings — one trace
    per mesh, not one per call site invocation (a fresh ``jax.jit`` of a
    fresh lambda re-traces the whole tree every checkpoint)."""
    return jax.jit(lambda t: t, out_shardings=replicated(mesh))


def host_replicated_copy(tree: Any, mesh: Optional[Mesh]) -> Any:
    """Host numpy copy of a device pytree, safe on multi-host meshes.

    ``jax.device_get`` alone raises on non-fully-addressable arrays
    (ZeRO-3/TP shards living on other hosts); replicate first via an
    identity jit with replicated out_shardings (an XLA all-gather over
    ICI/DCN), then pull the local replica.  The replicate is a
    COLLECTIVE: on a multi-host mesh every rank must call this at the
    same point.  Fully-addressable trees skip the gather entirely.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    fully_addressable = all(
        getattr(x, "is_fully_addressable", True) for x in leaves
    )
    if not fully_addressable and mesh is not None:
        tree = _replicate_fn(mesh)(tree)
    return jax.device_get(tree)


def data_axes(mesh: Mesh) -> tuple:
    """Mesh axes the global batch shards over.

    Both ``data`` and ``fsdp`` are batch-parallel axes (FSDP is data
    parallelism with parameters sharded over the same replicas); model
    axes (``tensor``/``sp``/...) see replicated batches.
    """
    return tuple(a for a in mesh.axis_names if a in ("data", "fsdp"))


def default_zero_axis(mesh: Mesh) -> Optional[str]:
    """ZeRO shards state over ``fsdp`` when the mesh has one, else ``data``;
    ``None`` on a pure model-parallel mesh (nothing to ZeRO-shard over)."""
    if "fsdp" in mesh.axis_names:
        return "fsdp"
    return "data" if "data" in mesh.axis_names else None


def batch_sharding(mesh: Mesh, axis=None) -> NamedSharding:
    """Shard the leading (batch) dim over the data axes; replicate the rest."""
    if axis is None:
        axis = data_axes(mesh)
    return NamedSharding(mesh, P(axis))


def shard_leaf_spec(
    shape: tuple,
    axis_size: int,
    axis_name: str,
    min_leaf_size: int = 2**12,
) -> P:
    """PartitionSpec for one leaf: biggest divisible axis or replicate."""
    return _merge_zero_axis(P(), shape, axis_size, axis_name, min_leaf_size)


def zero_state_shardings(
    state: Any,
    mesh: Mesh,
    zero_stage: int = 1,
    shard_axis: str = "data",
    min_leaf_size: int = 2**12,
) -> Any:
    """NamedShardings for a :class:`TrainState`-shaped pytree.

    * stage 0 — everything replicated (plain DDP).
    * stage 1 — optimizer state sharded, params replicated (≙ FairScale
      OSS; in JAX gradients are transient values inside one XLA program,
      so FairScale's stage-2 "shard gradients too" distinction collapses
      into the compiler's scheduling — ``RayShardedStrategy`` normalizes
      ``zero_stage=2`` to 1 with a warning).
    * stage 3 — params sharded as well (FSDP-style; XLA all-gathers just
      before use, reduce-scatters gradients).

    Works on abstract (ShapeDtypeStruct) or concrete pytrees.
    """
    if shard_axis not in mesh.axis_names:
        zero_stage = 0  # no batch-parallel axis to shard state over
        axis_size = 1
    else:
        axis_size = mesh.shape[shard_axis]

    def leaf_sharding(leaf, shard_it: bool) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shard_it:
            return replicated(mesh)
        spec = shard_leaf_spec(shape, axis_size, shard_axis, min_leaf_size)
        return NamedSharding(mesh, spec)

    from ray_lightning_tpu.core.module import TrainState

    if isinstance(state, TrainState):
        params_sh = jax.tree_util.tree_map(
            lambda l: leaf_sharding(l, zero_stage >= 3), state.params
        )
        opt_sh = jax.tree_util.tree_map(
            lambda l: leaf_sharding(l, zero_stage >= 1), state.opt_state
        )
        step_sh = replicated(mesh)
        return TrainState(params_sh, opt_sh, step_sh)
    # Generic pytree: apply the param rule everywhere.
    return jax.tree_util.tree_map(
        lambda l: leaf_sharding(l, zero_stage >= 1), state
    )


def _sanitize_spec(spec: P, mesh: Mesh) -> P:
    """Drop axis names the active mesh doesn't have (so one module can
    publish a full tp/sp layout and still run on a plain data mesh)."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(keep(e) for e in spec))


def _merge_zero_axis(
    spec: P, shape: tuple, axis_size: int, axis_name: str, min_leaf_size: int
) -> P:
    """Layer ZeRO sharding onto an existing (possibly TP) spec: shard the
    largest still-unsharded divisible dim over ``axis_name``."""
    if not shape or int(np.prod(shape)) < min_leaf_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [
        (dim, i) for i, dim in enumerate(shape)
        if entries[i] is None and dim % axis_size == 0
    ]
    if not candidates:
        return spec
    _, best = max(candidates)
    entries[best] = axis_name
    return P(*entries)


def _zero_axis_size(mesh: Mesh, zero_stage: int):
    """(zero_stage, axis_name, axis_size) with stage forced to 0 on a
    mesh with no batch-parallel axis to shard state over."""
    zero_axis = default_zero_axis(mesh)
    if zero_axis is None:
        return 0, None, 1
    return zero_stage, zero_axis, mesh.shape[zero_axis]


def _module_param_specs(module: Any, abstract_params: Any, mesh: Mesh) -> Any:
    """The module's published TP/SP PartitionSpecs (sanitized against the
    active mesh), or all-replicated specs if it publishes none."""
    spec_fn = getattr(module, "param_partition_specs", None)
    if spec_fn is not None:
        return jax.tree_util.tree_map(
            lambda s: _sanitize_spec(s, mesh),
            spec_fn(),
            is_leaf=lambda x: isinstance(x, P),
        )
    return jax.tree_util.tree_map(lambda _: P(), abstract_params)


def params_shardings_for_module(
    module: Any,
    abstract_params: Any,
    mesh: Mesh,
    zero_stage: int = 0,
    min_leaf_size: int = 2**12,
) -> Any:
    """NamedShardings for a bare params pytree (module TP specs + ZeRO-3).

    The params half of :func:`state_shardings_for_module` (which delegates
    here, so fit-time and eval-time param layouts can never diverge) —
    fit-less eval/predict must place a ZeRO-3 model with its *sharded*
    layout rather than replicating it onto every host (which would defeat
    param sharding at exactly the model sizes it targets).
    """
    zero_stage, zero_axis, axis_size = _zero_axis_size(mesh, zero_stage)
    param_specs = _module_param_specs(module, abstract_params, mesh)

    def finalize(spec: P, leaf) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if zero_stage >= 3:
            spec = _merge_zero_axis(
                spec, shape, axis_size, zero_axis, min_leaf_size
            )
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(
        finalize,
        param_specs,
        abstract_params,
        is_leaf=lambda x: isinstance(x, P),
    )


def state_shardings_for_module(
    module: Any,
    abstract_state: Any,
    mesh: Mesh,
    zero_stage: int = 0,
    min_leaf_size: int = 2**12,
) -> Any:
    """NamedShardings for a TrainState honoring the module's parallelism.

    Layering order (≙ how Megatron-LM + ZeRO compose, here as pure
    annotations):

    1. **module TP/SP specs** — ``module.param_partition_specs()`` if
       defined (a P-pytree congruent with params), sanitized against the
       active mesh;
    2. **ZeRO** — stage>=1 shards optimizer moments, stage>=3 also
       parameters, over the ``fsdp`` axis (or ``data`` if no fsdp axis),
       on the largest dim not already claimed by TP.

    Optimizer-state leaves inherit their parameter's spec by **key-path
    suffix matching**: an optax state like ``ScaleByAdamState.mu`` is a
    params-shaped subtree, so each moment leaf's path ends with the full
    path of its parameter — that spec (shape-checked) is reused.  Leaves
    with no param twin (step counts, scalars) fall back to the generic
    largest-axis rule.
    """
    from ray_lightning_tpu.core.module import TrainState

    if not isinstance(abstract_state, TrainState):
        return zero_state_shardings(
            abstract_state, mesh, zero_stage,
            default_zero_axis(mesh), min_leaf_size,
        )

    zero_stage, zero_axis, axis_size = _zero_axis_size(mesh, zero_stage)
    # TP specs (unmerged — the opt-state lookup below layers its own ZeRO
    # merge, which must start from the pre-ZeRO spec) and the final param
    # shardings, via the shared params path.
    param_specs = _module_param_specs(module, abstract_state.params, mesh)
    params_sh = params_shardings_for_module(
        module, abstract_state.params, mesh, zero_stage, min_leaf_size
    )

    def finalize(spec: P, leaf, shard_it: bool) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if shard_it:
            spec = _merge_zero_axis(
                spec, shape, axis_size, zero_axis, min_leaf_size
            )
        return NamedSharding(mesh, spec)

    # Path-indexed spec lookup for optimizer moments.
    flat_params = jax.tree_util.tree_flatten_with_path(abstract_state.params)[0]
    flat_specs = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    by_path = {
        tuple(path): (tuple(leaf.shape), spec)
        for (path, leaf), spec in zip(flat_params, flat_specs)
    }

    def opt_leaf(path, leaf) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        path = tuple(path)
        for i in range(len(path)):
            hit = by_path.get(path[i:])
            if hit is not None and hit[0] == shape:
                return finalize(hit[1], leaf, zero_stage >= 1)
        return finalize(
            shard_leaf_spec(shape, axis_size, zero_axis, min_leaf_size)
            if zero_stage >= 1 else P(),
            leaf,
            False,
        )

    opt_sh = jax.tree_util.tree_map_with_path(
        opt_leaf, abstract_state.opt_state
    )
    return TrainState(params_sh, opt_sh, replicated(mesh))


def stacked_batch_sharding(mesh: Mesh, axis=None) -> NamedSharding:
    """Sharding for a megastep's K pre-staged micro-batches stacked on a
    new leading axis: the STRIDE axis (dim 0) is replicated — every
    device sees all K inner steps in order — and the batch dim (dim 1)
    shards over the data axes exactly like a single batch would."""
    if axis is None:
        axis = data_axes(mesh)
    return NamedSharding(mesh, P(None, axis))


def stack_host_batches(batches: list) -> Any:
    """K shape-congruent host micro-batches → one numpy pytree with a
    new leading stride axis (leaf shape ``(K, B, ...)``).  The single
    host-side stacking rule for megastep strides — both the mesh path
    (:func:`make_global_stacked_batch`) and the single-device
    ``device_put`` path go through here so their semantics can't drift."""
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *batches
    )


def _batch_axes_prologue(mesh: Mesh, axis) -> Tuple[tuple, int]:
    """Shared head of the global-batch builders: normalize the data axes,
    enforce the multi-host no-data-axis guard, and compute the axis-size
    product.  Both :func:`make_global_batch` and
    :func:`make_global_stacked_batch` go through here so the placement
    contract can't drift between the single-batch and stride paths."""
    if axis is None:
        axis = data_axes(mesh)
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    if not axes and jax.process_count() > 1:
        # Replicated batch + per-host loader shards would silently hand
        # every host DIFFERENT rows under one "replicated" global array.
        raise ValueError(
            "Mesh has no data/fsdp axis to shard the batch over; a "
            "multi-host run would train on inconsistent data. Add a "
            "batch-parallel axis to mesh_axes."
        )
    axis_size = 1
    for a in axes:
        axis_size *= mesh.shape[a]
    return axes, axis_size


def _require_rows_divisible(
    what: str, global_rows: int, shaped: bool, axes: tuple, axis_size: int
) -> None:
    """The divisibility contract for the batch-row dim — must divide over
    the mesh's data axes or XLA raises an opaque placement error."""
    if not shaped or global_rows % axis_size != 0:
        raise ValueError(
            f"{what} (global {global_rows}) must be divisible "
            f"by the {axes!r} mesh axes size ({axis_size}). Pick a "
            f"batch_size that is a multiple of the number of devices."
        )


def make_global_stacked_batch(batches: list, mesh: Mesh, axis=None) -> Any:
    """K per-host numpy batch shards → one globally placed stride array.

    Stacks the K micro-batches leaf-wise on a new leading axis (host-side
    ``np.stack`` — the batches must be shape-congruent; the prefetch
    producer guarantees it) and ships the result as ONE ``jax.Array`` per
    leaf with :func:`stacked_batch_sharding` — a single host→device
    transfer per stride instead of K, feeding ``make_multi_step``'s
    ``lax.scan``.
    """
    axes, axis_size = _batch_axes_prologue(mesh, axis)
    sharding = stacked_batch_sharding(mesh, axes)

    stacked = stack_host_batches(batches)

    def to_global(x):
        # Batch rows live on dim 1 of the stacked leaf; the same
        # divisibility contract as make_global_batch applies there.
        global_rows = (
            x.shape[1] * jax.process_count() if x.ndim >= 2 else 0
        )
        _require_rows_divisible(
            "Stacked batch dim", global_rows, x.ndim >= 2, axes, axis_size
        )
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(to_global, stacked)


def make_global_batch(batch: Any, mesh: Mesh, axis=None) -> Any:
    """Per-host numpy batch shard → globally batch-sharded jax.Arrays.

    Every host holds ``global_batch / num_hosts`` examples (the
    DistributedSampler analogue in :mod:`..core.data`); this assembles the
    logical global array without any cross-host data movement — each
    host's shard lands on its own devices
    (``make_array_from_process_local_data``).
    """
    axes, axis_size = _batch_axes_prologue(mesh, axis)
    sharding = batch_sharding(mesh, axes)

    def to_global(x):
        x = np.asarray(x)
        # Global rows = local rows × num_processes.
        global_rows = x.shape[0] * jax.process_count() if x.ndim else 0
        _require_rows_divisible(
            "Batch leading dim", global_rows, x.ndim > 0, axes, axis_size
        )
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(to_global, batch)
