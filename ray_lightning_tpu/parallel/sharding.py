"""Sharding rules: ZeRO-style state sharding as GSPMD annotations.

≙ the reference's FairScale OSS / ShardedDataParallel / ShardedGradScaler
stack (``/root/reference/ray_lightning/ray_ddp_sharded.py:17-34``), which
wraps the model and optimizer in sharding *classes*.  On TPU the same
capability is a **compiler annotation** (SURVEY §7: "sharding is an
annotation, not a wrapper class"): we compute a ``NamedSharding`` for every
leaf of the train state and hand it to ``jax.jit`` as in/out shardings —
XLA then keeps optimizer state (ZeRO-1) and optionally parameters (ZeRO-3
/ FSDP) partitioned across the mesh, inserting reduce-scatter/all-gather
collectives over ICI where needed.

Leaf rule: shard the **largest axis divisible by the mesh axis size**;
small leaves (biases, scalars, layernorm gains) stay replicated — the
standard weight-update-sharding recipe (cf. "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", arXiv:2004.13336).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "replicated",
    "batch_sharding",
    "shard_leaf_spec",
    "zero_state_shardings",
    "make_global_batch",
]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def shard_leaf_spec(
    shape: tuple,
    axis_size: int,
    axis_name: str,
    min_leaf_size: int = 2**12,
) -> P:
    """PartitionSpec for one leaf: biggest divisible axis or replicate."""
    if not shape or int(np.prod(shape)) < min_leaf_size:
        return P()
    candidates = [
        (dim_size, i)
        for i, dim_size in enumerate(shape)
        if dim_size % axis_size == 0
    ]
    if not candidates:
        return P()
    _, best_axis = max(candidates)
    spec = [None] * len(shape)
    spec[best_axis] = axis_name
    return P(*spec)


def zero_state_shardings(
    state: Any,
    mesh: Mesh,
    zero_stage: int = 1,
    shard_axis: str = "data",
    min_leaf_size: int = 2**12,
) -> Any:
    """NamedShardings for a :class:`TrainState`-shaped pytree.

    * stage 0 — everything replicated (plain DDP).
    * stage 1/2 — optimizer state sharded, params replicated (≙ FairScale
      OSS; in JAX gradients are transient values inside one XLA program,
      so the stage-2 "shard gradients too" distinction collapses into the
      compiler's scheduling — nothing extra to annotate).
    * stage 3 — params sharded as well (FSDP-style; XLA all-gathers just
      before use, reduce-scatters gradients).

    Works on abstract (ShapeDtypeStruct) or concrete pytrees.
    """
    axis_size = mesh.shape[shard_axis]

    def leaf_sharding(leaf, shard_it: bool) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shard_it:
            return replicated(mesh)
        spec = shard_leaf_spec(shape, axis_size, shard_axis, min_leaf_size)
        return NamedSharding(mesh, spec)

    from ray_lightning_tpu.core.module import TrainState

    if isinstance(state, TrainState):
        params_sh = jax.tree_util.tree_map(
            lambda l: leaf_sharding(l, zero_stage >= 3), state.params
        )
        opt_sh = jax.tree_util.tree_map(
            lambda l: leaf_sharding(l, zero_stage >= 1), state.opt_state
        )
        step_sh = replicated(mesh)
        return TrainState(params_sh, opt_sh, step_sh)
    # Generic pytree: apply the param rule everywhere.
    return jax.tree_util.tree_map(
        lambda l: leaf_sharding(l, zero_stage >= 1), state
    )


def make_global_batch(batch: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Per-host numpy batch shard → globally batch-sharded jax.Arrays.

    Every host holds ``global_batch / num_hosts`` examples (the
    DistributedSampler analogue in :mod:`..core.data`); this assembles the
    logical global array without any cross-host data movement — each
    host's shard lands on its own devices
    (``make_array_from_process_local_data``).
    """
    sharding = batch_sharding(mesh, axis)
    axis_size = mesh.shape[axis]

    def to_global(x):
        x = np.asarray(x)
        # Global rows = local rows × num_processes; must divide over the
        # mesh's data axis or XLA raises an opaque placement error.
        global_rows = x.shape[0] * jax.process_count() if x.ndim else 0
        if x.ndim == 0 or global_rows % axis_size != 0:
            raise ValueError(
                f"Batch leading dim (global {global_rows}) must be divisible "
                f"by the {axis!r} mesh axis size ({axis_size}). Pick a "
                f"batch_size that is a multiple of the number of devices."
            )
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(to_global, batch)
