#!/usr/bin/env bash
# Format / lint entry point (≙ reference format.sh:1-150 + .style.yapf).
#
# Usage:
#   ./format.sh            # check changed files (vs origin/main or HEAD)
#   ./format.sh --all      # check the whole tree
#   ./format.sh --fix      # apply fixes (yapf, when installed) instead of
#                          # just checking
#
# Tool layering (the dev image may have no lint tools at all):
#   1. builtin checks (always run, zero deps): line length <= 88, no tabs
#      in indentation, no trailing whitespace, LF endings;
#   2. flake8 (pinned below, when importable) — the CI lint gate;
#   3. yapf --diff/--in-place (pinned below, when importable) with the
#      repo .style.yapf;
#   4. telemetry artifact schema gate (tools/check_telemetry_schema.py,
#      no deps beyond the package) — exporter/schema drift fails fast;
#      self-tests cover spans, the live plane, flight bundles AND the
#      bench host_overhead block (megastep dispatch accounting);
#   5. chaos-plane smoke (tools/chaos_sweep.py --selftest, no
#      subprocesses/fits) — the RLT_FAULT grammar, deterministic
#      matching, exactly-once markers and the file corruptors vs the
#      checkpoint verifier.  The full fault matrix lives in
#      "python tools/chaos_sweep.py" / "pytest -m chaos"; the serving
#      sibling (tools/chaos_serve_sweep.py --selftest) gates the serve
#      fault templates, brownout ladder and retry/hedge maths;
#   6. rlt-lint (tools/rlt_lint, stdlib-ast only) — the repo's own
#      invariants as machine checks: hot-path jit/host-sync bans,
#      guarded-by lock discipline, clock discipline, the RLT_* env-bus
#      registry, telemetry schema-key drift, thread hygiene.  Fixture
#      self-test first, then changed-scope lint (--all honored) against
#      the committed baseline.  Catalog: docs/STATIC_ANALYSIS.md.
# Missing optional tools are reported and skipped; the builtin layer
# still gates, so "./format.sh --all" is meaningful everywhere.
set -euo pipefail

FLAKE8_VERSION=7.1.1
YAPF_VERSION=0.40.2
FLAKE8_ARGS=(--max-line-length 88 --extend-ignore E203,W503,E731)

cd "$(dirname "$0")"

MODE=check
SCOPE=changed
for arg in "$@"; do
  case "$arg" in
    --all) SCOPE=all ;;
    --fix) MODE=fix ;;
    --check) MODE=check ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

# Untracked files are invisible to both ls-files (default) and diff —
# without the union a brand-new file ships past layers 1-3 unchecked
# until after commit.  ACMR keeps renamed-and-edited files (status R)
# in the changed scope; plain ACM drops them.
if [ "$SCOPE" = all ]; then
  mapfile -t FILES < <(
    { git ls-files '*.py'
      git ls-files --others --exclude-standard '*.py'; } | sort -u)
else
  base=$(git merge-base HEAD origin/main 2>/dev/null || echo HEAD)
  mapfile -t FILES < <(
    { git diff --name-only --diff-filter=ACMR "$base" -- '*.py'
      git ls-files --others --exclude-standard '*.py'; } | sort -u)
fi
[ ${#FILES[@]} -eq 0 ] && { echo "format.sh: no python files in scope"; exit 0; }

fail=0

# -- layer 1: builtin checks (no dependencies) -------------------------------
builtin_ok=1
python - "$MODE" "${FILES[@]}" <<'PYEOF' || builtin_ok=0
import sys

mode, files = sys.argv[1], sys.argv[2:]
bad = 0
for path in files:
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        continue
    if b"\r\n" in raw:
        print(f"{path}: CRLF line endings")
        bad += 1
    for lineno, line in enumerate(raw.decode("utf-8").splitlines(), 1):
        if len(line) > 88:
            print(f"{path}:{lineno}: line too long ({len(line)} > 88)")
            bad += 1
        if line != line.rstrip():
            print(f"{path}:{lineno}: trailing whitespace")
            bad += 1
        stripped = line.lstrip(" ")
        if stripped.startswith("\t"):
            print(f"{path}:{lineno}: tab indentation")
            bad += 1
sys.exit(1 if bad else 0)
PYEOF
[ "$builtin_ok" = 1 ] || fail=1

# -- layer 2: flake8 (pinned; the CI gate) -----------------------------------
if python -c "import flake8" 2>/dev/null; then
  python -m flake8 "${FLAKE8_ARGS[@]}" "${FILES[@]}" || fail=1
else
  echo "format.sh: flake8 not installed (pip install flake8==${FLAKE8_VERSION}) — skipped"
fi

# -- layer 3: yapf (pinned; auto-format) -------------------------------------
if python -c "import yapf" 2>/dev/null; then
  if [ "$MODE" = fix ]; then
    python -m yapf --in-place "${FILES[@]}"
  else
    # Advisory (non-gating) in check mode: the dev image ships no yapf,
    # so the tree cannot be guaranteed yapf-clean offline; flake8 and the
    # builtin layer are the enforced gates.
    python -m yapf --diff "${FILES[@]}" \
      || echo "format.sh: yapf would reformat (advisory) — run ./format.sh --fix"
  fi
else
  echo "format.sh: yapf not installed (pip install yapf==${YAPF_VERSION}) — skipped"
fi

# -- layer 4: telemetry artifact schemas (zero extra deps) -------------------
# Gates producer/schema drift: exporter self-test (spans, Chrome traces,
# heartbeat/event/log stream items, crash flight bundles, the bench
# host_overhead block), the committed flight-bundle fixture
# (tests/data/flight_bundle.json), and BENCH_*.json telemetry/fault/
# host_overhead blocks (tools/check_telemetry_schema.py).
python tools/check_telemetry_schema.py || fail=1
# Cross-round regression diff self-check (tools/rlt_bench_diff.py):
# the gated-key table + direction rules stay honest, so a drifted key
# path can't silently drop a metric from the trajectory diff.
python tools/rlt_bench_diff.py --selftest || fail=1

# -- layer 5: chaos-plane smoke (zero extra deps, no subprocess fits) --------
# Gates the fault-injection grammar + deterministic matching + the
# corruptor/verifier pair, so a drifted RLT_FAULT parser can't silently
# turn the recovery acceptance suite into a no-op.
python tools/chaos_sweep.py --selftest || fail=1
# Serving-plane sibling (tools/chaos_serve_sweep.py --selftest): the
# serve fault templates, the brownout ladder's hysteresis/probe logic,
# client retry backoff maths, and the scorecard->bench-block contract.
# The full serving matrix lives in "python tools/chaos_serve_sweep.py".
python tools/chaos_serve_sweep.py --selftest || fail=1

# -- layer 6: rlt-lint invariant checks (stdlib-ast, zero extra deps) --------
# The fixture matrix self-tests every rule (a rule edit that stops
# flagging its own positive fixtures fails here), then the lint runs at
# the same scope as the rest of this script: changed files by default,
# the whole tree under --all, gating either way.  Suppressions need a
# reason; grandfathered sites live in tools/rlt_lint/baseline.json and
# are enumerated in docs/STATIC_ANALYSIS.md.
python -m tools.rlt_lint --selftest || fail=1
if [ "$SCOPE" = all ]; then
  python -m tools.rlt_lint --all || fail=1
else
  python -m tools.rlt_lint --changed || fail=1
fi

if [ $fail -ne 0 ]; then
  echo "format.sh: FAILED (run ./format.sh --fix after installing tools)"
  exit 1
fi
echo "format.sh: OK"
