"""rlt_top — curses-free terminal live view of a run's heartbeat stream.

Reads either artifact the live plane produces (docs/OBSERVABILITY.md):

* ``live.json`` — the RunMonitor's driver-side snapshot (remote
  strategies; refreshed ~1/s under ``<root>/telemetry/``);
* ``heartbeats-rank<k>.jsonl`` — a worker/local fit's raw beat stream
  (queue-less LocalStrategy runs; pass the file or the telemetry dir);
* ``mpmd-live.json`` — the MPMD pipeline strategy's per-stage
  occupancy/bubble snapshot (MpmdStrategy fits);
* ``router-live.json`` — the disaggregated serving router's
  per-replica occupancy + failover snapshot (serve/dist fleets).

Renders a per-rank table (step, progress, step/data-wait ms, heartbeat
age, phase, status) plus the monitor's recent events, repainted with
plain ANSI — no curses, works in any terminal or ``watch``-style log.

Usage:
    python tools/rlt_top.py rlt_logs/telemetry           # auto-detect
    python tools/rlt_top.py rlt_logs/telemetry/live.json --interval 2
    python tools/rlt_top.py --once rlt_logs/telemetry    # single frame
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from collections import deque
from typing import Any, Dict, Optional

_CLEAR = "\x1b[H\x1b[2J"
# A live artifact older than this is marked STALE in the frame header:
# every producer rewrites its file at ~1s cadence, so a snapshot this
# old means the producer stopped — the gauges on screen are history,
# not state.
_STALE_AFTER_S = 10.0
_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _load_live_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_beats_jsonl(paths) -> Optional[Dict[str, Any]]:
    """Synthesize a live-snapshot-shaped dict from raw beat streams."""
    ranks: Dict[str, Dict[str, Any]] = {}
    now = time.time()
    for path in paths:
        last = None
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        last = line
        except OSError:
            continue
        if not last:
            continue
        try:
            beat = json.loads(last)
        except ValueError:
            continue
        beat.pop("type", None)
        beat["age_s"] = round(now - beat.get("ts", now), 1)
        beat["status"] = "done" if beat.get("done") else "ok"
        ranks[str(beat.get("rank", 0))] = beat
    if not ranks:
        return None
    return {"ts": now, "ranks_reporting": len(ranks), "ranks": ranks,
            "events": [], "aborted": False,
            "beats": sum(r.get("seq", 0) for r in ranks.values())}


def load_snapshot(path: str) -> Optional[Dict[str, Any]]:
    """live.json file, a beats .jsonl, or a directory holding either."""
    if os.path.isdir(path):
        # Newest-mtime wins among the live artifacts: a stale
        # live.json from an earlier SPMD fit in the same root must not
        # shadow the actively-refreshed mpmd/serve snapshot (each
        # producer rewrites its own file every refresh).
        candidates = []
        for name in ("live.json", "serve-live.json", "router-live.json",
                     "mpmd-live.json"):
            full = os.path.join(path, name)
            try:
                candidates.append((os.path.getmtime(full), full))
            except OSError:
                continue
        if candidates:
            return _load_live_json(max(candidates)[1])
        return _load_beats_jsonl(
            sorted(glob.glob(os.path.join(path, "heartbeats-rank*.jsonl")))
        )
    if path.endswith(".jsonl"):
        return _load_beats_jsonl([path])
    return _load_live_json(path)


def _spark(values, width: int = 32) -> str:
    """Unicode sparkline of the last ``width`` values, min-max scaled."""
    vals = [float(v) for v in values
            if isinstance(v, (int, float))][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    top = len(_SPARK_CHARS) - 1
    return "".join(
        _SPARK_CHARS[int((v - lo) / span * top)] for v in vals
    )


def note_history(snapshot: Optional[Dict[str, Any]],
                 history: Dict[str, deque]) -> None:
    """Accumulate capacity series across frames for the sparkline
    pane.  Main-loop state — ``render`` itself stays a pure function
    of (snapshot, history)."""
    if not snapshot:
        return
    serve = snapshot.get("serve") or {}
    cap = serve.get("capacity")
    if not isinstance(cap, dict):
        return
    for key in ("tokens_per_s", "utilization",
                "headroom_tokens_per_s", "queue_depth"):
        value = cap.get(key)
        if isinstance(value, (int, float)):
            history.setdefault(key, deque(maxlen=240)).append(
                float(value)
            )


def _stale_tag(snapshot: Dict[str, Any], now: float) -> str:
    """The staleness marker (satellite fix: panes used to render
    instantaneous gauges silently when a live.json stopped
    refreshing)."""
    ts = snapshot.get("ts")
    if not isinstance(ts, (int, float)):
        return ""
    age = now - ts
    if age <= _STALE_AFTER_S:
        return ""
    return f"  ** STALE {age:.0f}s — source stopped refreshing **"


def _fmt(value: Any, width: int) -> str:
    if value is None:
        text = "-"
    elif isinstance(value, float):
        text = f"{value:.1f}"
    else:
        text = str(value)
    return text[:width].rjust(width)


def _render_serve(serve: Dict[str, Any]) -> list:
    """The serving pane (``serve-live.json`` / engine snapshots):
    queue/slot/block occupancy and the SLO latency percentiles."""
    g = serve.get("gauges", {})
    c = serve.get("counters", {})
    spec = ""
    if c.get("spec_drafted"):
        # Speculative engines: draft-acceptance is the tokens/s lever.
        spec = (f"  spec acc {g.get('spec_acceptance_rate', 0):.2f}"
                f" ({c.get('spec_accepted', 0)}/{c.get('spec_drafted', 0)})")
    lines = [
        "",
        f"serve: queue {g.get('queue_depth', 0):.0f}  slots "
        f"{g.get('slots_active', 0):.0f}/{g.get('num_slots', 0):.0f}  "
        f"blocks {g.get('blocks_live', 0):.0f}/{g.get('num_blocks', 0):.0f}"
        f"  done {c.get('completed', 0)}  rej {c.get('rejected', 0)}"
        f"  preempt {c.get('preempted', 0)}" + spec,
    ]
    latency = serve.get("latency", {})
    if latency:
        lines.append(
            "         " + "  ".join(
                f"{family} p50/p99 "
                f"{s.get('p50_ms', 0):.1f}/{s.get('p99_ms', 0):.1f}ms"
                for family, s in sorted(latency.items())
            )
        )
    lines += _render_prefix(serve)
    lines += _render_lora(serve)
    lines += _render_phases(serve)
    return lines


def _num(value: Any, fmt: str = "{:.1f}") -> str:
    return fmt.format(value) if isinstance(value, (int, float)) else "-"


def _render_capacity(serve: Dict[str, Any],
                     slo: Optional[Dict[str, Any]] = None,
                     history: Optional[Dict[str, deque]] = None) -> list:
    """The capacity pane (capacity-plane engines export a ``capacity``
    block — serve/capacity.py): measured load vs the predicted
    ceiling, leading saturation indicators, history sparklines, and
    the burn-rate state of each SLO."""
    cap = serve.get("capacity")
    if not cap:
        return []
    eta = cap.get("kv_exhaustion_eta_s")
    lines = [
        f"capacity: {_num(cap.get('tokens_per_s'))} tok/s"
        f" / ceiling {_num(cap.get('capacity_tokens_per_s'))}"
        f"  util {_num(cap.get('utilization'), '{:.2f}')}"
        f"  headroom {_num(cap.get('headroom_tokens_per_s'))}"
        f"  rej {_num(cap.get('rejection_rate'), '{:.2f}')}"
        + (f"  kv_eta {_num(eta, '{:.0f}')}s"
           if isinstance(eta, (int, float)) else ""),
    ]
    if history:
        for key, label in (("tokens_per_s", "tok/s"),
                           ("utilization", "util "),
                           ("queue_depth", "queue")):
            series = history.get(key)
            if series is not None and len(series) >= 2:
                lines.append(f"          {label} {_spark(series)}")
    if slo:
        lines.append("slo:      " + "  ".join(
            f"{name} burn {state.get('burn_rate', 0.0):.1f}x"
            f"/{state.get('alerts_total', 0)} alert(s)"
            + ("  FIRING" if state.get("firing") else "")
            for name, state in sorted(slo.items())
        ))
    return lines


def _render_prefix(serve: Dict[str, Any]) -> list:
    """The prefix-cache pane (engines with prefix-aware KV reuse):
    hit rate, resident blocks, and the claimed-vs-inserted block
    flow — how much prefill the cache is actually saving."""
    p = serve.get("prefix")
    if not p:
        return []
    c = serve.get("counters", {})
    chunks = ""
    if c.get("prefill_chunks"):
        chunks = f"  chunks {c['prefill_chunks']}"
    return [
        f"prefix:  hit {p.get('hit_rate', 0.0):.2f} "
        f"({p.get('hits', 0)}/{p.get('lookups', 0)})"
        f"  cached {p.get('cached_blocks', 0)}blk"
        f"  claimed {p.get('blocks_claimed', 0)}"
        f"  inserted {p.get('blocks_inserted', 0)}"
        f"  evicted {p.get('blocks_evicted', 0)}" + chunks,
    ]


def _render_lora(serve: Dict[str, Any]) -> list:
    """The multi-tenant LoRA pane (engines with an adapter pool):
    pool occupancy, the fairness spread, and the busiest tenants'
    lifetime token/completion counts."""
    g = serve.get("gauges", {})
    adapters = serve.get("adapters")
    if not adapters and "lora_adapters_loaded" not in g:
        return []
    head = (f"lora:    {g.get('lora_adapters_loaded', 0):.0f} loaded"
            f" ({g.get('lora_slots_free', 0):.0f} slots free)"
            f"  fairness {g.get('lora_fairness_spread', 1.0):.2f}")
    lines = [head]
    if adapters:
        top = sorted(adapters.items(),
                     key=lambda kv: -kv[1].get("tokens_out", 0))[:6]
        lines.append("         " + "  ".join(
            f"{name} {entry.get('tokens_out', 0)}tok/"
            f"{entry.get('completed', 0)}done"
            for name, entry in top
        ))
    return lines


_PHASE_ORDER = ("queue_wait", "placement", "prefill_compute",
                "handoff_transfer", "decode_admission", "first_token")


def _render_phases(serve: Dict[str, Any]) -> list:
    """The critical-path phase pane (tracing engines export a
    ``phases`` block in their snapshot): where each request's TTFT
    went, as live p50/p95 per phase."""
    phases = serve.get("phases")
    if not phases:
        return []
    lines = ["phases:  " + "  ".join(
        f"{name} p50/p95 "
        f"{phases[name].get('p50_ms', 0):.1f}/"
        f"{phases[name].get('p95_ms', 0):.1f}ms"
        for name in _PHASE_ORDER if name in phases
    )]
    extra = sorted(set(phases) - set(_PHASE_ORDER))
    if extra:
        lines.append("         " + "  ".join(
            f"{name} p50/p95 "
            f"{phases[name].get('p50_ms', 0):.1f}/"
            f"{phases[name].get('p95_ms', 0):.1f}ms"
            for name in extra
        ))
    return lines


def _render_router(router: Dict[str, Any]) -> list:
    """The disaggregated-fleet pane (``router-live.json``): per-replica
    occupancy + failover/respawn counters — the view an operator
    watches during a replica death."""
    c = router.get("counters", {})
    lines = [
        "",
        f"router: routed {c.get('routed', 0)}"
        f"  done {c.get('completed', 0)}"
        f"  rej {c.get('rejected', 0)}"
        f"  failovers {c.get('failovers', 0)}"
        f" ({c.get('failed_over_requests', 0)} req)"
        f"  deaths r{c.get('replica_deaths', 0)}/p"
        f"{c.get('worker_deaths', 0)}"
        f"  respawns {c.get('prefill_respawns', 0)}",
    ]
    fleet = router.get("capacity")
    if fleet:
        lines.append(
            f"fleet:  {_num(fleet.get('tokens_per_s'))} tok/s"
            f" / ceiling {_num(fleet.get('capacity_tokens_per_s'))}"
            f"  util {_num(fleet.get('utilization'), '{:.2f}')}"
            f"  headroom {_num(fleet.get('headroom_tokens_per_s'))}"
            f"  ({fleet.get('replicas_reporting', 0)} reporting)"
        )
    lines.append(
        "replica  alive  inflight  slots      blocks   beat_age  "
        "spec_acc  adapters"
    )
    for r in router.get("replicas", []):
        slots = (f"{r.get('slots_active', 0):.0f}/"
                 f"{r.get('num_slots', 0):.0f}"
                 if "num_slots" in r else "-")
        blocks = (f"{r.get('blocks_free', 0):.0f} free"
                  if "blocks_free" in r else "-")
        acc = r.get("spec_acceptance_rate")
        lines.append(
            f"{str(r.get('id', '?')):>7}"
            + f"{'yes' if r.get('alive') else 'DEAD':>7}"
            + _fmt(r.get("inflight"), 10)
            + slots.rjust(7)
            + blocks.rjust(13)
            + _fmt(r.get("last_beat_age_s"), 11)
            + _fmt(None if acc is None else acc, 10)
            + _fmt(r.get("adapters"), 10)
        )
    workers = router.get("workers", [])
    if workers:
        lines.append(
            "prefill: " + "  ".join(
                f"{w.get('id')}[{'up' if w.get('alive') else 'DEAD'}"
                f" pend {w.get('pending', 0)}"
                + (f" adp {w['adapters']}" if "adapters" in w else "")
                + "]"
                for w in workers
            )
        )
    return lines


def _render_mpmd(mpmd: Dict[str, Any]) -> list:
    """The MPMD pipeline pane (``mpmd-live.json``): schedule shape plus
    per-stage step/occupancy/bubble — the pipeline-balance view."""
    lines = [
        "",
        f"mpmd: {mpmd.get('schedule', '?')}"
        + (f" x{mpmd['interleave']}" if mpmd.get("interleave", 1) > 1
           else "")
        + f"  stages {mpmd.get('n_stages', '?')}"
        f"  micro {mpmd.get('n_micro', '?')}",
        "stage   step    occ%  bubble%    busy_ms     loss",
    ]
    for item in mpmd.get("stages", []):
        occ = item.get("stage_occupancy")
        bub = item.get("bubble_fraction")
        lines.append(
            f"{item.get('stage', '?'):>5}"
            + _fmt(item.get("step"), 7)
            + _fmt(None if occ is None else 100 * occ, 8)
            + _fmt(None if bub is None else 100 * bub, 9)
            + _fmt(1e3 * item.get("busy_s", 0.0), 11)
            + _fmt(item.get("loss"), 9)
        )
    return lines


def _render_programs(programs: Dict[str, Any]) -> list:
    """The compiled-executable pane (``program_ledger.snapshot()``):
    one row per (site, variant) — dispatch counts, compile wall,
    cost-analysis FLOPs/bytes, scratch footprint — plus the recompile-
    forensics tail naming the argument that forced each recompile."""
    rows = programs.get("programs", [])
    if not rows:
        return []
    total_s = programs.get("compile_time_total_s", 0.0)
    lines = [
        "",
        f"programs: {len(rows)} executable(s), "
        f"compile {total_s:.2f}s total"
        + (f"  ({programs['dropped']} dropped)"
           if programs.get("dropped") else ""),
        "site                      var    calls  comp_s     mflops"
        "    arg_mb   tmp_mb",
    ]
    for row in sorted(rows, key=lambda r: (r.get("site", ""),
                                           r.get("variant", 0))):
        flops = row.get("flops")
        arg_b = row.get("argument_bytes")
        tmp_b = row.get("temp_bytes")
        lines.append(
            f"{str(row.get('site', '?'))[:25]:<25}"
            + _fmt(row.get("variant"), 4)
            + _fmt(row.get("ncalls"), 9)
            + _fmt(row.get("compile_s"), 8)
            + _fmt(None if flops is None else flops / 1e6, 11)
            + _fmt(None if arg_b is None else arg_b / 1e6, 10)
            + _fmt(None if tmp_b is None else tmp_b / 1e6, 9)
        )
    recompiles = programs.get("recompiles") or []
    if recompiles:
        lines += ["", "recent recompiles:"]
        for ev in recompiles[-5:]:
            lines.append(
                f"  [{ev.get('kind', '?'):<9}] {ev.get('site', '?')}: "
                f"{ev.get('argument', '?')}"
                + (f" {ev['old']} -> {ev['new']}"
                   if ev.get("old") and ev.get("new") else "")
            )
    return lines


def render(snapshot: Optional[Dict[str, Any]], source: str,
           history: Optional[Dict[str, deque]] = None,
           now: Optional[float] = None) -> str:
    """One text frame (pure function of its inputs — tested directly).
    ``now`` stamps snapshot age (STALE marking); ``history`` feeds the
    capacity sparklines (accumulated by :func:`note_history`)."""
    stamp = time.strftime("%H:%M:%S")
    if not snapshot:
        return f"rlt_top {stamp} — no live data at {source} (yet?)\n"
    if now is None:
        now = time.time()
    stale = _stale_tag(snapshot, now)
    if "mpmd" in snapshot and "ranks" not in snapshot:
        return (f"rlt_top {stamp} — mpmd pipeline{stale}\n"
                + "\n".join(_render_mpmd(snapshot["mpmd"])) + "\n")
    if "serve" in snapshot and "ranks" not in snapshot:
        lines = _render_serve(snapshot["serve"])
        lines += _render_capacity(snapshot["serve"],
                                  snapshot.get("slo"), history)
        if snapshot.get("programs"):
            lines += _render_programs(snapshot["programs"])
        return (f"rlt_top {stamp} — serving engine{stale}\n"
                + "\n".join(lines) + "\n")
    if "router" in snapshot and "ranks" not in snapshot:
        return (f"rlt_top {stamp} — serve router "
                f"({len(snapshot['router'].get('replicas', []))} "
                f"replica(s)){stale}\n"
                + "\n".join(_render_router(snapshot["router"])) + "\n")
    lines = [
        f"rlt_top {stamp} — {snapshot.get('ranks_reporting', 0)} rank(s), "
        f"{snapshot.get('beats', 0)} beats"
        + ("  ** ABORTED **" if snapshot.get("aborted") else "")
        + stale,
        "",
        "rank   step   epoch  progress  step_ms  wait_ms   age_s  "
        "phase       status",
    ]
    for rank in sorted(snapshot.get("ranks", {}), key=int):
        b = snapshot["ranks"][rank]
        lines.append(
            f"{rank:>4}"
            + _fmt(b.get("global_step"), 7)
            + _fmt(b.get("epoch"), 7)
            + _fmt(b.get("progress"), 9)
            + _fmt(b.get("step_time_ms"), 9)
            + _fmt(b.get("data_wait_ms"), 9)
            + _fmt(b.get("age_s"), 8)
            + "  " + str(b.get("phase", "-"))[:10].ljust(10)
            + "  " + str(b.get("status", "-"))
        )
    if snapshot.get("serve"):
        lines += _render_serve(snapshot["serve"])
        lines += _render_capacity(snapshot["serve"],
                                  snapshot.get("slo"), history)
    if snapshot.get("router"):
        lines += _render_router(snapshot["router"])
    if snapshot.get("mpmd"):
        lines += _render_mpmd(snapshot["mpmd"])
    if snapshot.get("programs"):
        lines += _render_programs(snapshot["programs"])
    events = snapshot.get("events") or []
    if events:
        lines += ["", "recent events:"]
        for ev in events[-8:]:
            msg = ev.get("message") or ev.get("error") or ev.get("bundle", "")
            lines.append(
                f"  [{ev.get('kind', '?'):<14}] rank {ev.get('rank')}: "
                f"{msg}"[:110]
            )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Terminal live view of the rlt heartbeat stream."
    )
    ap.add_argument(
        "path", nargs="?", default="rlt_logs/telemetry",
        help="live.json, heartbeats-rank*.jsonl, or the telemetry dir",
    )
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="render a single frame and exit")
    args = ap.parse_args(argv)

    history: Dict[str, deque] = {}
    try:
        while True:
            snapshot = load_snapshot(args.path)
            note_history(snapshot, history)
            frame = render(snapshot, args.path, history=history)
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write(_CLEAR + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
