#!/usr/bin/env python
"""Pinned repro for the concurrent-trials dispatch wedge.

``tests/test_tune.py::test_concurrent_trials_with_real_fits`` wedges
~2/3 of runs on a loaded 2-core container — two LocalStrategy fits in
concurrent trial threads starve each other's jax dispatch (scheduler
starvation, NOT interpreter state: round 13 measured it in FRESH
subprocesses; round 11's whole-suite-state theory is retired).  The
test is slow-marked out of tier-1 (round 16) so the 870s budget stops
paying ~360s of worst-case timeouts; THIS script keeps the flake
measurable on demand:

    python tools/repro_tune_wedge.py              # 10 attempts, 180s cap
    python tools/repro_tune_wedge.py -n 30 -t 60  # tighter sweep

Each attempt runs the test body in a fresh interpreter with a fresh
tmp dir (exactly the quarantine harness) and is scored pass / wedge
(timeout) / fail (nonzero exit — NOT the known flake, investigate).
Exit code: 0 if every attempt passed, 2 if any wedged, 1 on real
failures.  Run it when touching tuning/strategy threading, or to
re-measure the wedge rate on new hardware before un-quarantining.
"""

import argparse
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TEST = os.path.join(_REPO, "tests", "test_tune.py")

_SCRIPT = (
    "import importlib.util, sys\n"
    "spec = importlib.util.spec_from_file_location('t', sys.argv[1])\n"
    "mod = importlib.util.module_from_spec(spec)\n"
    "spec.loader.exec_module(mod)\n"
    "mod._concurrent_real_fits_body(sys.argv[2])\n"
)


def one_attempt(timeout_s: float, workdir: str):
    """Returns ('pass'|'wedge'|'fail', seconds, detail)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SCRIPT, _TEST, workdir],
            capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=_REPO,
        )
    except subprocess.TimeoutExpired:
        return "wedge", time.monotonic() - t0, f"timeout {timeout_s}s"
    dt = time.monotonic() - t0
    if proc.returncode != 0:
        return "fail", dt, (f"rc={proc.returncode}\n"
                            f"{proc.stdout}\n{proc.stderr}")
    return "pass", dt, ""


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--attempts", type=int, default=10)
    ap.add_argument("-t", "--timeout", type=float, default=180.0,
                    help="per-attempt wall cap in seconds (the "
                    "quarantine harness used 180)")
    args = ap.parse_args()

    counts = {"pass": 0, "wedge": 0, "fail": 0}
    for i in range(1, args.attempts + 1):
        with tempfile.TemporaryDirectory(prefix="tune_wedge_") as d:
            verdict, dt, detail = one_attempt(args.timeout, d)
        counts[verdict] += 1
        print(f"attempt {i:2d}/{args.attempts}: {verdict:5s} "
              f"({dt:6.1f}s)" + (f"  {detail.splitlines()[0]}"
                                 if detail else ""), flush=True)
        if verdict == "fail":
            print(detail, file=sys.stderr)
    n = args.attempts
    print(f"\nwedge rate: {counts['wedge']}/{n} "
          f"({100.0 * counts['wedge'] / n:.0f}%)  "
          f"pass {counts['pass']}  fail {counts['fail']}")
    if counts["fail"]:
        return 1
    return 2 if counts["wedge"] else 0


if __name__ == "__main__":
    sys.exit(main())
