"""rlt_bench_diff — cross-round BENCH_*.json trajectory diff.

Rounds are comparable only through their gated keys (tokens/s,
recompile pins, speedup ratios, overhead percentages — the numbers
``bench*.py`` gates on and ``telemetry/schema.py`` shapes).  This tool
diffs those keys between any two round artifacts, direction-aware:

* ``higher`` keys (throughput, speedups, coverage) regress when the
  new round drops more than the threshold;
* ``lower`` keys (latency, overhead pcts) regress when it rises;
* ``zero`` keys (steady-state recompile pins) regress on ANY non-zero
  value — the zero-recompile contract has no tolerance.

Regressions are flagged LOUDLY (``!! REGRESSION``, non-zero exit under
``--strict``); blocks absent from either round (feature landed later,
or a probe was skipped) diff as added/removed, never as failures.

Usage:
    python tools/rlt_bench_diff.py BENCH_r08.json BENCH_r09.json
    python tools/rlt_bench_diff.py --latest          # two newest rounds
    python tools/rlt_bench_diff.py --trajectory      # all rounds, table
    python tools/rlt_bench_diff.py --selftest        # format.sh layer

stdlib-only, jax-free (runs anywhere the artifacts land).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# Gated keys: (dotted path, direction).  Directions: "higher" is
# better, "lower" is better, "zero" is a pin (any non-zero regresses).
GATED_KEYS: Tuple[Tuple[str, str], ...] = (
    ("value", "higher"),                       # the headline metric
    ("serve.requests_per_sec", "higher"),
    ("serve.tokens_per_sec", "higher"),
    ("serve.p50_token_latency_ms", "lower"),
    ("serve.p99_token_latency_ms", "lower"),
    ("serve.continuous_vs_sequential", "higher"),
    ("serve.recompiles_steady_state", "zero"),
    ("spec_decode.vs_baseline", "higher"),
    ("spec_decode.acceptance_rate", "higher"),
    ("spec_decode.recompiles_steady_state", "zero"),
    ("trace.coverage", "higher"),
    ("trace.overhead_pct", "lower"),
    ("multi_lora.vs_baseline", "higher"),
    ("multi_lora.fairness_spread", "higher"),
    ("multi_lora.recompiles_steady_state", "zero"),
    ("serve_disagg.vs_monolith", "higher"),
    ("serve_disagg.recompiles_steady_state", "zero"),
    ("serve_disagg.chaos.lost_requests", "zero"),
    ("prefix_cache.ttft_speedup", "higher"),
    ("prefix_cache.hit_rate", "higher"),
    ("prefix_cache.recompiles_steady_state", "zero"),
    ("chunked_prefill.recompiles_steady_state", "zero"),
    ("slo.prediction_error_pct", "lower"),
    ("slo.alerts_cold", "zero"),
    ("slo.recompiles_steady_state", "zero"),
    ("comm_overlap.loss_rel_diff", "lower"),
    ("comm_overlap.recompiles_step_end", "zero"),
    ("comm_overlap.recompiles_overlap", "zero"),
    ("comm_overlap.collectives_before_last_dot_overlap", "higher"),
    ("comm_overlap.mpmd_wire_ratio", "higher"),
)

# Relative change below which a higher/lower key is noise, not signal.
DEFAULT_THRESHOLD_PCT = 10.0
# Denominator floor: near-zero baselines diff by absolute delta
# against this instead of exploding the percentage.
_ABS_FLOOR = 1e-9


def lookup(doc: Dict[str, Any], path: str) -> Optional[float]:
    node: Any = doc
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def _delta_pct(old: float, new: float) -> Optional[float]:
    if abs(old) < _ABS_FLOOR:
        return None  # no meaningful relative change off a ~0 baseline
    return 100.0 * (new - old) / abs(old)


def diff_docs(old: Dict[str, Any], new: Dict[str, Any],
              threshold_pct: float = DEFAULT_THRESHOLD_PCT
              ) -> List[Dict[str, Any]]:
    """One row per gated key present in either round."""
    rows = []
    for path, direction in GATED_KEYS:
        a, b = lookup(old, path), lookup(new, path)
        if a is None and b is None:
            continue
        row: Dict[str, Any] = {
            "key": path, "direction": direction, "old": a, "new": b,
        }
        if a is None:
            row["status"] = "added"
        elif b is None:
            row["status"] = "removed"
        elif direction == "zero":
            # The pin: the OLD value being non-zero was that round's
            # failure; the diff only polices the new one.
            row["status"] = "regression" if b != 0 else "ok"
            row["delta_pct"] = None
        else:
            pct = _delta_pct(a, b)
            row["delta_pct"] = pct
            if pct is None:
                # ~0 baseline: judge the absolute move (overhead pcts
                # hovering around the noise floor live here).
                worse = (b < a) if direction == "higher" else (b > a)
                big = abs(b - a) > threshold_pct / 10.0
                row["status"] = "regression" if worse and big else "ok"
            else:
                worse = -pct if direction == "higher" else pct
                if worse > threshold_pct:
                    row["status"] = "regression"
                elif worse < -threshold_pct:
                    row["status"] = "improved"
                else:
                    row["status"] = "ok"
        rows.append(row)
    return rows


def _round_files(root: str = ".") -> List[str]:
    def key(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    files = [f for f in glob.glob(os.path.join(root, "BENCH_r*.json"))
             if key(f) >= 0]
    return sorted(files, key=key)


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: bench artifact is not an object")
    return doc


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v:.3f}".rstrip("0").rstrip(".")


def print_diff(rows: List[Dict[str, Any]], old_name: str,
               new_name: str) -> int:
    regressions = 0
    print(f"bench diff: {old_name} -> {new_name}")
    print(f"{'key':<42} {'old':>10} {'new':>10} {'delta':>9}  status")
    for row in rows:
        pct = row.get("delta_pct")
        delta = f"{pct:+.1f}%" if isinstance(pct, float) else "-"
        status = row["status"]
        if status == "regression":
            regressions += 1
            status = "!! REGRESSION"
        print(f"{row['key']:<42} {_fmt(row['old']):>10} "
              f"{_fmt(row['new']):>10} {delta:>9}  {status}")
    if regressions:
        print(f"\n{regressions} REGRESSION(S) in gated keys "
              f"({old_name} -> {new_name})")
    else:
        print("\nno gated-key regressions")
    return regressions


def print_trajectory(paths: List[str]) -> None:
    docs = [(os.path.basename(p), _load(p)) for p in paths]
    print("gated-key trajectory across rounds")
    header = f"{'key':<42}" + "".join(
        f"{name.replace('BENCH_', '').replace('.json', ''):>9}"
        for name, _ in docs
    )
    print(header)
    for path, _ in GATED_KEYS:
        values = [lookup(doc, path) for _, doc in docs]
        if all(v is None for v in values):
            continue
        print(f"{path:<42}"
              + "".join(f"{_fmt(v):>9}" for v in values))


def self_test() -> int:
    old = {
        "value": 10.0,
        "serve": {"requests_per_sec": 10.0, "tokens_per_sec": 160.0,
                  "p50_token_latency_ms": 20.0,
                  "p99_token_latency_ms": 40.0,
                  "recompiles_steady_state": 0},
        "trace": {"coverage": 1.0, "overhead_pct": 0.1},
    }
    new = json.loads(json.dumps(old))
    new["serve"]["requests_per_sec"] = 8.0          # -20%: regression
    new["serve"]["p50_token_latency_ms"] = 30.0     # +50%: regression
    new["serve"]["tokens_per_sec"] = 200.0          # +25%: improved
    new["serve"]["recompiles_steady_state"] = 2     # pin broken
    new["slo"] = {"prediction_error_pct": 5.0,
                  "alerts_cold": 0,
                  "recompiles_steady_state": 0}     # added block
    new["comm_overlap"] = {"loss_rel_diff": 0.002,
                           "recompiles_step_end": 0,
                           "recompiles_overlap": 1,  # pin broken
                           "collectives_before_last_dot_overlap": 54,
                           "mpmd_wire_ratio": 3.9}   # added block
    rows = {r["key"]: r for r in diff_docs(old, new)}
    problems = []

    def expect(key, status):
        got = rows.get(key, {}).get("status")
        if got != status:
            problems.append(f"{key}: expected {status}, got {got}")

    expect("serve.requests_per_sec", "regression")
    expect("serve.p50_token_latency_ms", "regression")
    expect("serve.tokens_per_sec", "improved")
    expect("serve.recompiles_steady_state", "regression")
    expect("serve.p99_token_latency_ms", "ok")
    expect("value", "ok")
    expect("slo.prediction_error_pct", "added")
    expect("slo.alerts_cold", "added")
    expect("comm_overlap.loss_rel_diff", "added")
    expect("comm_overlap.recompiles_overlap", "added")
    if "spec_decode.vs_baseline" in rows:
        problems.append("absent-in-both block produced a row")
    # Direction sanity: a zero pin that HOLDS must not flag, and a
    # near-zero overhead baseline must use the absolute-move rule.
    ok_rows = {r["key"]: r for r in diff_docs(new, new)}
    broken_pins = {"serve.recompiles_steady_state",
                   "comm_overlap.recompiles_overlap"}
    for key, row in ok_rows.items():
        if row["status"] == "regression" and key not in broken_pins:
            problems.append(f"self-diff regressed {key}")
    shrunk = json.loads(json.dumps(new))
    shrunk["trace"]["overhead_pct"] = 0.0
    grown = json.loads(json.dumps(new))
    grown["trace"]["overhead_pct"] = 5.0
    if {r["key"]: r for r in diff_docs(shrunk, grown)}[
            "trace.overhead_pct"]["status"] != "regression":
        problems.append("overhead_pct absolute-move rule missed a rise")
    if problems:
        print("rlt_bench_diff selftest FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("rlt_bench_diff selftest OK "
          f"({len(GATED_KEYS)} gated keys)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Direction-aware diff of gated BENCH_*.json keys."
    )
    ap.add_argument("old", nargs="?", help="older round artifact")
    ap.add_argument("new", nargs="?", help="newer round artifact")
    ap.add_argument("--latest", action="store_true",
                    help="diff the two newest BENCH_r*.json rounds")
    ap.add_argument("--trajectory", action="store_true",
                    help="table of every gated key across all rounds")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD_PCT,
                    help="relative regression threshold (pct)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero when any gated key regressed")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)

    if args.selftest:
        return self_test()
    if args.trajectory:
        paths = _round_files()
        if len(paths) < 2:
            print("need at least two BENCH_r*.json rounds")
            return 2
        print_trajectory(paths)
        return 0
    if args.latest:
        paths = _round_files()
        if len(paths) < 2:
            print("need at least two BENCH_r*.json rounds")
            return 2
        args.old, args.new = paths[-2], paths[-1]
    if not (args.old and args.new):
        ap.print_usage()
        return 2
    rows = diff_docs(_load(args.old), _load(args.new), args.threshold)
    regressions = print_diff(rows, os.path.basename(args.old),
                             os.path.basename(args.new))
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
