"""Serving-plane chaos sweep: the fault x recovery matrix end-to-end.

Two modes (mirrors ``tools/chaos_sweep.py``, which owns the TRAINING
fault matrix — this tool owns the serving plane):

* ``--selftest`` (wired into ``format.sh`` layer 5): fast, jax-free
  checks of the sweep's own machinery — every matrix cell's
  ``RLT_FAULT`` string parses, the brownout ladder's hysteresis and
  half-open probe logic, the client retry policy's backoff maths, and
  the scorecard-to-bench-block contract
  (``telemetry/schema.py::validate_bench_serve_chaos``).
* default: the full serving matrix — for each cell a real inproc
  fleet (2 decode replicas, prefill workers where the cell needs
  them) with the fault injected deterministically, asserting the
  affected streams complete with BITWISE parity against an
  uninterrupted single-engine reference, zero lost requests, and the
  cell's recovery counters.  Exits non-zero on any unrecovered cell.

The matrix::

    drain-migration   planned drain -> live KV migration (zero
                      recomputed prefill, parity at temperature>0)
    kill-failover     abrupt death  -> recompute failover + dedup
    blackhole-beat    beat partition -> beat-loss failover while the
                      victim's stream keeps racing (client dedup)
    torn-handoff      torn prefill handoff payload -> failed-feed
                      re-dispatch
    shm-vanish        KV segment unlinked between send and read ->
                      failed-feed re-dispatch
    slow-hedge        straggler replica -> hedged resubmit, first
                      winner, loser cancelled
    brownout          sustained overload -> ladder climbs to shed
                      (typed replies, priority traffic survives),
                      recovery descends and re-admits

Usage::

    python tools/chaos_serve_sweep.py --selftest
    python tools/chaos_serve_sweep.py                 # full matrix
    python tools/chaos_serve_sweep.py --only drain-migration
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_P1 = list(range(1, 9))
_P2 = list(range(9, 17))
_MAX_NEW = 30


# ---------------------------------------------------------------------------
# --selftest: the sweep's own machinery (no jax, no fleets)
# ---------------------------------------------------------------------------

#: Every fault template a matrix cell injects ("{member}" is filled
#: with the discovered victim id at run time).
_CELL_FAULTS = {
    "blackhole-beat": "blackhole@point:beat,replica:{member},once:0",
    "torn-handoff": "exc@point:handoff_read,nth:1",
    "shm-vanish": "shm_vanish@point:handoff_send,nth:1",
    "slow-hedge": "slow@point:replica_tick,replica:{member},secs:0.4,once:0",
}


def _selftest() -> list:
    problems: list = []

    def check(cond: bool, what: str) -> None:
        if not cond:
            problems.append(what)

    # Every cell's grammar must parse (a typo'd spec silently matches
    # nothing and "proves" recovery paths that never fired).
    from ray_lightning_tpu.fault import inject

    for name, tmpl in _CELL_FAULTS.items():
        try:
            specs = inject.parse_faults(tmpl.format(member="r0"))
            check(len(specs) == 1, f"{name}: expected 1 spec")
        except ValueError as e:
            problems.append(f"{name}: fault template does not parse: {e}")

    # Brownout ladder: one-rung moves, hysteresis, dwell, probe.
    from ray_lightning_tpu.serve.brownout import BrownoutLadder

    t = [0.0]
    ladder = BrownoutLadder(min_dwell_s=1.0, probe_every_s=5.0,
                            clock=lambda: t[0])
    check(ladder.observe(0.99) == 1, "ladder: first climb not immediate")
    check(ladder.observe(2.0) == 1, "ladder: climbed without dwell")
    t[0] = 1.0
    check(ladder.observe(0.96) == 2, "ladder: rung 2 climb")
    t[0] = 2.0
    check(ladder.observe(1.0) == 3, "ladder: rung 3 climb")
    t[0] = 3.0
    check(ladder.observe(0.96) == 3, "ladder: descended above exit")
    check(ladder.observe(0.80) == 2, "ladder: rung 3 -> 2 descent")
    t[0] = 4.0
    check(ladder.observe(0.10) == 1, "ladder: rung 2 -> 1 descent")
    t[0] = 5.0
    check(ladder.observe(0.10) == 0, "ladder: rung 1 -> 0 descent")
    check(ladder.allow_probe() is True, "ladder: first probe denied")
    check(ladder.allow_probe() is False, "ladder: probe window ignored")
    t[0] = 11.0
    check(ladder.allow_probe() is True, "ladder: probe never re-armed")
    for bad_kwargs in ({"enter": (0.9, 0.8, 1.0)}, {"exit_margin": 0.0},
                       {"max_new_cap": 0}, {"enter": (0.5, 0.9)}):
        try:
            BrownoutLadder(**bad_kwargs)
            problems.append(f"ladder: {bad_kwargs} should not construct")
        except ValueError:
            pass

    # Client retry policy: env resolution and the backoff series.
    from ray_lightning_tpu.serve.client import RetryPolicy

    os.environ["RLT_RETRY_MAX"] = "5"
    os.environ["RLT_RETRY_BACKOFF_S"] = "0.2"
    os.environ["RLT_HEDGE"] = "1"
    try:
        pol = RetryPolicy.from_env()
        check(pol.max_attempts == 5 and pol.backoff_s == 0.2
              and pol.hedge is True, "retry: env resolution")
    finally:
        for k in ("RLT_RETRY_MAX", "RLT_RETRY_BACKOFF_S", "RLT_HEDGE"):
            os.environ.pop(k, None)
    pol = RetryPolicy(backoff_s=0.05, backoff_max_s=0.3)
    pauses = [min(pol.backoff_max_s, pol.backoff_s * 2 ** (a - 1))
              for a in range(1, 5)]
    check(pauses == [0.05, 0.1, 0.2, 0.3], f"retry: backoff series {pauses}")

    # Scorecard -> bench-block contract: the summary the full sweep
    # prints must satisfy the schema the bench artifact is gated on.
    from ray_lightning_tpu.telemetry.schema import validate_bench_serve_chaos

    block = {
        "migrations": 1, "migration_ttr_s": 0.4, "failover_ttr_s": 1.2,
        "migration_vs_failover": 3.0, "lost_requests": 0,
        "migration_re_emitted_tokens": 0, "parity": True,
        "recompiles_steady_state": 0,
    }
    errs = validate_bench_serve_chaos(block)
    check(not errs, f"scorecard: green block rejected: {errs}")
    check(bool(validate_bench_serve_chaos({**block, "lost_requests": -1})),
          "scorecard: negative lost_requests accepted")
    return problems


# ---------------------------------------------------------------------------
# Full matrix: real inproc fleets with injected faults
# ---------------------------------------------------------------------------

_MODEL = None
_REF = None


def _model():
    """One tiny GPT, built once and reused by every cell."""
    global _MODEL
    if _MODEL is None:
        import jax

        from ray_lightning_tpu.models.gpt import GPT, GPTConfig

        cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                        seq_len=64, warmup_steps=1)
        m = GPT(cfg, attn_impl="xla")
        _MODEL = (m, m.init_params(jax.random.PRNGKey(0)))
    return _MODEL


def _serve_cfg():
    from ray_lightning_tpu.serve.engine import ServeConfig

    return ServeConfig(num_slots=2, block_size=8)


def _reference():
    """Uninterrupted single-engine token streams — the parity pin."""
    global _REF
    if _REF is None:
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = _model()
        eng = ServeEngine(m, params, _serve_cfg())
        _REF = (eng.generate(_P1, _MAX_NEW, temperature=0.7),
                eng.generate(_P2, _MAX_NEW))
        eng.stop()
    return _REF


def _await(cond, timeout_s: float, poll_s: float = 0.01) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False


def _row(name: str) -> dict:
    return {"name": name, "ok": False, "error": "", "ttr_s": None,
            "re_emitted": 0, "parity": None, "lost": 0, "notes": "",
            "wall_s": 0.0}


def _launch(n_prefill: int = 0, **router_kwargs):
    from ray_lightning_tpu.serve.client import ServeClient
    from ray_lightning_tpu.serve.dist import launch_inproc_fleet

    m, params = _model()
    fleet = launch_inproc_fleet(
        m, params, _serve_cfg(), n_replicas=2, n_prefill=n_prefill,
        lost_after_s=0.5, **router_kwargs,
    )
    return fleet, ServeClient(fleet.queue_handle())


def _stream_started(fleet, client, rid, min_tokens: int = 3):
    """Wait until ``rid`` is placed and has streamed a few tokens;
    returns its replica id."""

    def started():
        track = fleet.router._inflight.get(rid)
        return (track is not None and track.replica is not None
                and len(client._pending[rid].tokens) >= min_tokens)

    if not _await(started, 60.0):
        raise RuntimeError(f"{rid} never started streaming")
    return fleet.router._inflight[rid].replica


def _finish(row, client, fleet, rids, ref, t_disturb=None):
    """Collect results, book parity / dedup / TTR / loss into the row."""
    outs = []
    for rid in rids:
        try:
            outs.append(client.result(rid, timeout=120))
        except Exception as e:  # noqa: BLE001 - booked as a lost request
            row["lost"] += 1
            row["error"] = f"{rid}: {type(e).__name__}: {e}"
            outs.append(None)
    row["parity"] = all(
        o is not None and o == r for o, r in zip(outs, ref)
    )
    row["re_emitted"] = client.re_emitted_tokens
    if not row["parity"] and not row["error"]:
        row["error"] = "token stream diverged from the reference"
    return outs


def _steady_state_recompiles(fleet, client) -> int:
    """Post-recovery wave: a second request pair must reuse every
    compiled program (the bench pins this too; here it proves the
    recovery path left no cold executables behind)."""
    from ray_lightning_tpu.telemetry import compile_event_count

    before = compile_event_count()
    r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
    r2 = client.submit(_P2, _MAX_NEW)
    client.result(r1, timeout=120)
    client.result(r2, timeout=120)
    return compile_event_count() - before


def _cell_drain_migration() -> dict:
    """Planned drain: live KV migration, zero recomputed prefill."""
    row = _row("drain-migration")
    t0 = time.monotonic()
    os.environ["RLT_MIGRATE_ON_DRAIN"] = "1"
    fleet, client = _launch()
    try:
        r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
        r2 = client.submit(_P2, _MAX_NEW)
        victim = _stream_started(fleet, client, r1)
        n_at_kill = len(client._pending[r1].tokens)
        t_kill = time.monotonic()
        next(r for r in fleet.replicas if r.id == victim).kill(hard=False)
        if _await(lambda: len(client._pending[r1].tokens) > n_at_kill,
                  60.0):
            row["ttr_s"] = round(time.monotonic() - t_kill, 3)
        _finish(row, client, fleet, (r1, r2), _reference())
        c = fleet.router.counters
        steady = _steady_state_recompiles(fleet, client)
        row["notes"] = (f"migrations={c['migrations']} "
                        f"failovers={c['failovers']} "
                        f"steady_recompiles={steady}")
        if not row["error"]:
            if c["migrations"] < 1:
                row["error"] = "no migration frame landed"
            elif c["failovers"]:
                row["error"] = "drain fell back to recompute failover"
            elif row["re_emitted"]:
                row["error"] = (
                    f"{row['re_emitted']} re-emitted tokens — "
                    "prefill was recomputed"
                )
            elif steady:
                row["error"] = f"{steady} steady-state recompiles"
            else:
                row["ok"] = True
    except Exception as e:  # noqa: BLE001 - scorecard, not traceback
        row["error"] = f"{type(e).__name__}: {e}"
    finally:
        os.environ.pop("RLT_MIGRATE_ON_DRAIN", None)
        client.close()
        fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_kill_failover() -> dict:
    """Abrupt death: recompute failover, client dedups re-emits."""
    row = _row("kill-failover")
    t0 = time.monotonic()
    fleet, client = _launch()
    try:
        r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
        r2 = client.submit(_P2, _MAX_NEW)
        victim = _stream_started(fleet, client, r1)
        n_at_kill = len(client._pending[r1].tokens)
        t_kill = time.monotonic()
        next(r for r in fleet.replicas if r.id == victim).kill(hard=True)
        if _await(lambda: len(client._pending[r1].tokens) > n_at_kill,
                  60.0):
            row["ttr_s"] = round(time.monotonic() - t_kill, 3)
        _finish(row, client, fleet, (r1, r2), _reference())
        c = fleet.router.counters
        steady = _steady_state_recompiles(fleet, client)
        row["notes"] = (f"failovers={c['failovers']} "
                        f"re_emitted={row['re_emitted']} "
                        f"steady_recompiles={steady}")
        if not row["error"]:
            if c["failovers"] < 1:
                row["error"] = "death never failed over"
            elif steady:
                row["error"] = f"{steady} steady-state recompiles"
            else:
                row["ok"] = True
    except Exception as e:  # noqa: BLE001
        row["error"] = f"{type(e).__name__}: {e}"
    finally:
        client.close()
        fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_blackhole_beat() -> dict:
    """Beat partition: the victim keeps streaming while the router
    (rightly) fails over — exactly-once tokens via client dedup."""
    row = _row("blackhole-beat")
    t0 = time.monotonic()
    fleet, client = _launch()
    try:
        r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
        r2 = client.submit(_P2, _MAX_NEW)
        victim = _stream_started(fleet, client, r1)
        os.environ["RLT_FAULT"] = (
            _CELL_FAULTS["blackhole-beat"].format(member=victim)
        )
        if not _await(
                lambda: fleet.router.counters["failovers"] >= 1, 30.0):
            row["error"] = "partitioned replica never declared lost"
        _finish(row, client, fleet, (r1, r2), _reference())
        c = fleet.router.counters
        row["notes"] = (f"failovers={c['failovers']} "
                        f"re_emitted={row['re_emitted']}")
        if not row["error"]:
            row["ok"] = True
    except Exception as e:  # noqa: BLE001
        row["error"] = f"{type(e).__name__}: {e}"
    finally:
        os.environ.pop("RLT_FAULT", None)
        client.close()
        fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_torn_handoff() -> dict:
    """Torn prefill handoff payload: the replica reports the rid on
    its failed feed and the router re-dispatches the prefill."""
    row = _row("torn-handoff")
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rlt_serve_torn_") as tmp:
        os.environ["RLT_FAULT"] = _CELL_FAULTS["torn-handoff"]
        os.environ["RLT_FAULT_STATE"] = tmp
        fleet, client = _launch(n_prefill=1)
        try:
            r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
            r2 = client.submit(_P2, _MAX_NEW)
            _finish(row, client, fleet, (r1, r2), _reference())
            row["notes"] = (
                f"resubmits={sum(t.resubmits for t in fleet.router._inflight.values())}"
            )
            if not row["error"]:
                row["ok"] = True
        except Exception as e:  # noqa: BLE001
            row["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
            client.close()
            fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_shm_vanish() -> dict:
    """KV tmpfs segment unlinked between handoff send and read: the
    consumer's read fails retryably and the router re-dispatches."""
    row = _row("shm-vanish")
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rlt_serve_shm_") as tmp:
        os.environ["RLT_FAULT"] = _CELL_FAULTS["shm-vanish"]
        os.environ["RLT_FAULT_STATE"] = tmp
        fleet, client = _launch(n_prefill=1)
        try:
            # Force the shm transport for every payload size so the
            # vanish has a segment to hit (inproc fleet = same host).
            for w in fleet.workers:
                w.runner._shm_threshold = 1
            r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
            r2 = client.submit(_P2, _MAX_NEW)
            _finish(row, client, fleet, (r1, r2), _reference())
            if not row["error"]:
                row["ok"] = True
        except Exception as e:  # noqa: BLE001
            row["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
            client.close()
            fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_slow_hedge() -> dict:
    """Straggler replica: a hedged resubmit races a second replica,
    the first terminal beat wins, the loser is cancelled."""
    row = _row("slow-hedge")
    t0 = time.monotonic()
    fleet, client = _launch()
    try:
        r1 = client.submit(_P1, _MAX_NEW, temperature=0.7)
        victim = _stream_started(fleet, client, r1, min_tokens=1)
        os.environ["RLT_FAULT"] = (
            _CELL_FAULTS["slow-hedge"].format(member=victim)
        )
        if not client.hedge(r1):
            row["error"] = "hedge resubmit refused"
        _finish(row, client, fleet, (r1,), _reference()[:1])
        c = fleet.router.counters
        # The client's result arrives on the direct reply socket; the
        # router only learns the winner from the next done beat, so
        # give the beat-driven loser cancel a moment to land.
        _await(lambda: c["hedge_cancels"] >= 1, 15.0)
        row["notes"] = (f"hedges={c['hedges']} "
                        f"hedge_cancels={c['hedge_cancels']} "
                        f"re_emitted={row['re_emitted']}")
        if not row["error"]:
            if c["hedges"] < 1:
                row["error"] = "router never placed the hedge"
            elif c["hedge_cancels"] < 1:
                row["error"] = "losing copy was never cancelled"
            else:
                row["ok"] = True
    except Exception as e:  # noqa: BLE001
        row["error"] = f"{type(e).__name__}: {e}"
    finally:
        os.environ.pop("RLT_FAULT", None)
        client.close()
        fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


def _cell_brownout() -> dict:
    """Sustained overload: the ladder climbs to shed, best-effort
    traffic gets typed retryable replies while priority traffic
    admits; recovery descends and re-admits the retried request."""
    from ray_lightning_tpu.serve.brownout import BrownoutLadder
    from ray_lightning_tpu.serve.client import ServeRejected
    from ray_lightning_tpu.serve.dist.handoff import make_beat_item

    row = _row("brownout")
    t0 = time.monotonic()
    fleet, client = _launch(
        brownout=BrownoutLadder(min_dwell_s=0.0, probe_every_s=600.0),
    )

    def _forge_util(tokens_per_s: float, target_level: int) -> bool:
        """Feed the router capacity evidence over the REAL beat wire
        (the ladder only moves on evidence) until it reaches the
        target level."""

        def push_and_check():
            fleet.router.beat_handle.put(make_beat_item(
                "decode", "r0",
                snapshot={"capacity": {
                    "tokens_per_s": tokens_per_s,
                    "capacity_tokens_per_s": 100.0,
                }},
            ))
            snap = fleet.router.snapshot()
            return snap.get("brownout_level") == target_level

        return _await(push_and_check, 30.0, poll_s=0.05)

    try:
        if not _forge_util(100.0, 3):
            raise RuntimeError("ladder never climbed to shed")
        # First best-effort request IS the half-open probe (admitted by
        # contract); the second must get the typed shed reply.
        probe = client.submit(_P1, _MAX_NEW, temperature=0.7, priority=0)
        shed_rid = client.submit(_P2, _MAX_NEW, priority=0)
        try:
            client.result(shed_rid, timeout=30)
            row["error"] = "best-effort request admitted at shed level"
        except ServeRejected:
            pass
        # Priority traffic still admits at level 3.
        prio = client.submit(_P2, _MAX_NEW, priority=1)
        out_probe = client.result(probe, timeout=120)
        out_prio = client.result(prio, timeout=120)
        ref = _reference()
        row["parity"] = (out_probe == ref[0] and out_prio == ref[1])
        if not row["parity"]:
            row["error"] = "admitted streams diverged from the reference"
        # Recovery: low-utilization evidence descends the ladder and
        # the retried best-effort request admits again.
        if not row["error"] and not _forge_util(0.0, 0):
            row["error"] = "ladder never recovered to healthy"
        if not row["error"]:
            retried = client.submit(_P2, _MAX_NEW, priority=0)
            if client.result(retried, timeout=120) != ref[1]:
                row["error"] = "post-recovery retry diverged"
        c = fleet.router.counters
        row["notes"] = (f"shed={c['shed']} "
                        f"level_max=3")
        if not row["error"]:
            if c["shed"] < 1:
                row["error"] = "no typed shed reply was counted"
            else:
                row["ok"] = True
    except Exception as e:  # noqa: BLE001
        row["error"] = f"{type(e).__name__}: {e}"
    finally:
        client.close()
        fleet.close()
    row["wall_s"] = round(time.monotonic() - t0, 1)
    return row


_MATRIX = [
    ("drain-migration", _cell_drain_migration),
    ("kill-failover", _cell_kill_failover),
    ("blackhole-beat", _cell_blackhole_beat),
    ("torn-handoff", _cell_torn_handoff),
    ("shm-vanish", _cell_shm_vanish),
    ("slow-hedge", _cell_slow_hedge),
    ("brownout", _cell_brownout),
]


def _print_scorecard(rows: list) -> None:
    width = max(len(r["name"]) for r in rows) + 2
    print(f"\n{'cell':<{width}}{'result':<11}{'wall':<7}{'ttr_s':<8}"
          f"{'lost':<6}{'re_emit':<9}{'parity':<8}notes")
    for r in rows:
        verdict = "RECOVERED" if r["ok"] else "FAILED"
        ttr = "-" if r["ttr_s"] is None else r["ttr_s"]
        par = "-" if r["parity"] is None else str(r["parity"])
        print(f"{r['name']:<{width}}{verdict:<11}{r['wall_s']:<7}"
              f"{ttr:<8}{r['lost']:<6}{r['re_emitted']:<9}{par:<8}"
              f"{r['notes'] or '-'}")
        if r["error"]:
            print(f"{'':<{width}}  {r['error']}")
    good = sum(r["ok"] for r in rows)
    lost = sum(r["lost"] for r in rows)
    print(f"\nchaos_serve_sweep: {good}/{len(rows)} cells recovered, "
          f"{lost} lost request(s)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Serving-plane fault-injection sweep "
        "(docs/FAULT_TOLERANCE.md, docs/SERVING.md)."
    )
    ap.add_argument("--selftest", action="store_true",
                    help="fast sweep-machinery self-checks (no fleets)")
    ap.add_argument("--only", default=None,
                    help="run a single matrix cell by name")
    args = ap.parse_args(argv)

    if args.selftest:
        problems = _selftest()
        for p in problems:
            print(f"chaos_serve_sweep selftest: {p}", file=sys.stderr)
        print("chaos_serve_sweep selftest: "
              + ("FAILED" if problems else "OK"))
        return 1 if problems else 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rows = []
    for name, cell in _MATRIX:
        if args.only and name != args.only:
            continue
        print(f"chaos_serve_sweep: running {name} ...", flush=True)
        rows.append(cell())
    if not rows:
        print(f"chaos_serve_sweep: no cell named {args.only!r}",
              file=sys.stderr)
        return 2
    _print_scorecard(rows)
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
