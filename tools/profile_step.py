"""Profile one GPT train step on the current backend and rank op costs.

Usage: ``python tools/profile_step.py [--config gpt2_small|tiny] [--steps 6]``

Captures a ``jax.profiler.trace`` around chained jitted steps (chained
inside the trace so per-dispatch tunnel overhead — ~4 ms on the remote
platform — amortizes; see docs/PERFORMANCE.md "Profiling recipe"),
parses the trace's ``trace.json.gz``, and prints the top XLA ops by
total self-duration plus a coarse bucket breakdown (matmul / attention
kernels / CE kernels / layernorm-elementwise / optimizer / copies).

This is the measurement half of the perf loop; bench.py is the score.
"""

from __future__ import annotations

import argparse
import collections
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# Trace parsing lives in the telemetry subsystem now (shared with
# tools/trace_summary.py); these aliases keep the harness's historical
# local names working.
from ray_lightning_tpu.telemetry.trace_parse import (  # noqa: E402
    collect,
    op_bucket as _bucket,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2_small",
                    choices=["gpt2_small", "tiny"])
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=0)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    from bench import _detect_backend
    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.models.gpt import GPT, GPTConfig
    from ray_lightning_tpu.parallel.step_fns import build_train_step

    on_tpu = _detect_backend() == "tpu"
    if args.config == "gpt2_small":
        cfg = GPTConfig(vocab_size=50304, n_layer=12, n_head=12,
                        d_model=768, seq_len=1024, warmup_steps=10)
        batch = args.batch_size or 16
    else:
        cfg = GPTConfig.tiny()
        batch = args.batch_size or 8
    module = GPT(cfg, attn_impl="auto", remat=on_tpu)
    module.precision = "bf16"

    params = module.init_params(jax.random.PRNGKey(0))
    tx = module.configure_optimizers()
    state = TrainState.create(params, tx)
    step = build_train_step(module, tx, mesh=None)
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch, cfg.seq_len + 1)), jnp.int32)
    rng = jax.random.PRNGKey(0)
    batch_d = {"tokens": tokens}

    # Warm up (compile) outside the trace.
    for _ in range(2):
        state, logs = step(state, batch_d, rng)
    float(jax.device_get(logs["loss"]))

    trace_dir = tempfile.mkdtemp(prefix="rlt_profile_")
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        for _ in range(args.steps):
            state, logs = step(state, batch_d, rng)
        loss = float(jax.device_get(logs["loss"]))
    wall = time.perf_counter() - t0
    print(f"# {args.steps} steps in {wall*1e3:.1f} ms "
          f"({wall/args.steps*1e3:.1f} ms/step), loss={loss:.4f}, "
          f"backend={jax.default_backend()}", file=sys.stderr)

    durs = collect(trace_dir)
    total = sum(durs.values())
    buckets: dict = collections.defaultdict(float)
    for name, d in durs.items():
        buckets[_bucket(name)] += d
    print("== buckets (% of op time) ==")
    for b, d in sorted(buckets.items(), key=lambda kv: -kv[1]):
        print(f"{100*d/total:6.2f}%  {d/1e3/args.steps:8.2f} ms/step  {b}")
    print(f"== top {args.top} ops ==")
    for name, d in sorted(durs.items(), key=lambda kv: -kv[1])[:args.top]:
        print(f"{100*d/total:6.2f}%  {d/1e3/args.steps:8.2f} ms/step  "
              f"{name[:90]}")


if __name__ == "__main__":
    main()
