"""Schema gate for telemetry artifacts (wired into ``format.sh``).

Two passes, both fast and dependency-free beyond the package itself:

1. **self-test** — build a real ``SpanTracer``, record nested spans,
   export JSONL + Chrome trace to a temp dir, and validate both through
   ``telemetry/schema.py``.  If a producer and the written-down schema
   drift apart, this fails before any artifact ships;
2. **artifact scan** — validate the ``telemetry`` block of every
   ``BENCH_*.json`` in the repo root (absent blocks are fine —
   pre-telemetry rounds legitimately lack them) and any span/trace
   exports passed as arguments.

Exit code 0 = all schemas hold.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_lightning_tpu.telemetry.schema import (  # noqa: E402
    validate_bench_telemetry,
    validate_chrome_trace,
    validate_span_jsonl,
)
from ray_lightning_tpu.telemetry.spans import SpanTracer  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def self_test() -> list:
    """Exporters must produce what the schema promises."""
    tracer = SpanTracer(enabled=True, maxlen=64, rank=0)
    with tracer.span("outer", tag="self-test"):
        with tracer.span("inner"):
            pass
    tracer.instant("marker", detail=1)
    problems = []
    with tempfile.TemporaryDirectory(prefix="rlt_schema_") as tmp:
        jsonl = os.path.join(tmp, "spans.jsonl")
        chrome = os.path.join(tmp, "trace.json")
        tracer.export_jsonl(jsonl)
        tracer.export_chrome(chrome)
        with open(jsonl) as f:
            problems += validate_span_jsonl(f.readlines(), "self-test jsonl")
        with open(chrome) as f:
            problems += validate_chrome_trace(
                json.load(f), "self-test chrome"
            )
    return problems


def scan_bench_files() -> list:
    problems = []
    for path in sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as e:
            problems.append(f"{name}: not JSON ({e})")
            continue
        block = doc.get("telemetry")
        if block is None:
            continue  # pre-telemetry round
        problems += validate_bench_telemetry(block, f"{name}:telemetry")
    return problems


def scan_paths(paths) -> list:
    problems = []
    for path in paths:
        name = os.path.basename(path)
        try:
            if path.endswith(".jsonl"):
                with open(path) as f:
                    problems += validate_span_jsonl(f.readlines(), name)
            else:
                with open(path) as f:
                    problems += validate_chrome_trace(json.load(f), name)
        except (OSError, ValueError) as e:
            problems.append(f"{name}: unreadable ({e})")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Validate telemetry artifact schemas "
        "(span JSONL, Chrome traces, BENCH_*.json telemetry blocks)."
    )
    ap.add_argument("paths", nargs="*",
                    help="extra span .jsonl / chrome .json files to check")
    args = ap.parse_args(argv)

    problems = self_test() + scan_bench_files() + scan_paths(args.paths)
    if problems:
        for p in problems:
            print(f"check_telemetry_schema: {p}", file=sys.stderr)
        print(f"check_telemetry_schema: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    print("check_telemetry_schema: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
