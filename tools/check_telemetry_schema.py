"""Schema gate for telemetry artifacts (wired into ``format.sh``).

Two passes, both fast and dependency-free beyond the package itself:

1. **self-test** — drive the REAL producers (``SpanTracer`` exports,
   ``heartbeat.make_beat``, ``monitor.make_event``, a
   ``FlightRecorder`` crash bundle, ``logs.make_log_item``) and
   validate their output through ``telemetry/schema.py``.  If a
   producer and the written-down schema drift apart, this fails before
   any artifact ships;
2. **artifact scan** — validate the ``telemetry`` block of every
   ``BENCH_*.json`` in the repo root (absent blocks are fine —
   pre-telemetry rounds legitimately lack them), the committed flight-
   bundle fixture (``tests/data/flight_bundle.json``), and any
   span/trace/bundle files passed as arguments.

Exit code 0 = all schemas hold.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_lightning_tpu.telemetry.schema import (  # noqa: E402
    validate_bench_fault,
    validate_bench_host_overhead,
    validate_bench_chunked_prefill,
    validate_bench_comm_overlap,
    validate_bench_mpmd,
    validate_bench_multi_lora,
    validate_bench_opt_state,
    validate_bench_prefix_cache,
    validate_bench_programs,
    validate_bench_residual_policy,
    validate_bench_serve,
    validate_bench_serve_chaos,
    validate_bench_serve_disagg,
    validate_bench_slo,
    validate_bench_spec_decode,
    validate_bench_telemetry,
    validate_bench_trace,
    validate_capacity_snapshot,
    validate_chrome_trace,
    validate_flight_bundle,
    validate_mpmd_snapshot,
    validate_mpmd_xfer,
    validate_program_snapshot,
    validate_recompile_record,
    validate_router_snapshot,
    validate_serve_kv_handoff,
    validate_serve_reply,
    validate_serve_request,
    validate_serve_snapshot,
    validate_slo_alert,
    validate_span_jsonl,
    validate_stream_item,
    validate_timeseries_point,
    validate_trace_context,
)
from ray_lightning_tpu.telemetry.spans import SpanTracer  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_BUNDLE = os.path.join(
    REPO_ROOT, "tests", "data", "flight_bundle.json"
)


class _StubCtx:
    """Loop-context stand-in: the live-plane producers are duck-typed
    over these fields exactly so this gate stays jax-free."""

    global_step = 3
    micro_step = 7
    current_epoch = 1
    progress = 9
    phase = "train"
    telemetry_dir = None


def self_test() -> list:
    """Exporters must produce what the schema promises."""
    tracer = SpanTracer(enabled=True, maxlen=64, rank=0)
    with tracer.span("outer", tag="self-test"):
        with tracer.span("inner"):
            pass
    tracer.instant("marker", detail=1)
    problems = []
    with tempfile.TemporaryDirectory(prefix="rlt_schema_") as tmp:
        jsonl = os.path.join(tmp, "spans.jsonl")
        chrome = os.path.join(tmp, "trace.json")
        tracer.export_jsonl(jsonl)
        tracer.export_chrome(chrome)
        with open(jsonl) as f:
            problems += validate_span_jsonl(f.readlines(), "self-test jsonl")
        with open(chrome) as f:
            problems += validate_chrome_trace(
                json.load(f), "self-test chrome"
            )
        problems += _self_test_live_plane(tmp)
    return problems


def _self_test_live_plane(tmp: str) -> list:
    """Heartbeat/event/log producers + a real crash bundle."""
    from ray_lightning_tpu.telemetry.flight_recorder import FlightRecorder
    from ray_lightning_tpu.telemetry.heartbeat import make_beat
    from ray_lightning_tpu.telemetry.logs import make_log_item
    from ray_lightning_tpu.telemetry.monitor import make_event

    problems = []
    ctx = _StubCtx()
    beat = make_beat(rank=0, seq=1, ctx=ctx)
    problems += validate_stream_item(beat, "self-test heartbeat")
    final = make_beat(rank=0, seq=2, ctx=ctx, done=True)
    problems += validate_stream_item(final, "self-test final heartbeat")
    problems += validate_stream_item(
        make_event("stall", 2, age_s=1.5, message="self-test"),
        "self-test event",
    )
    # Recovery-plane event shapes (fault/drain + restart governance):
    # the drain event a worker publishes, and the strategy's backoff /
    # elastic_restart / ckpt_corrupt records seeded into the monitor.
    problems += validate_stream_item(
        make_event("drain", 0, message="self-test drain",
                   ckpt="/tmp/drain-step-00000007.ckpt"),
        "self-test drain event",
    )
    problems += validate_stream_item(
        make_event("backoff", -1, delay_s=1.5, attempt=1,
                   message="self-test backoff"),
        "self-test backoff event",
    )
    problems += validate_stream_item(
        make_event("elastic_restart", -1, attempt=1, recover_s=0.8,
                   ckpt="/tmp/restart-epoch-000001.ckpt",
                   message="self-test restart"),
        "self-test restart event",
    )
    problems += validate_stream_item(
        make_event("ckpt_corrupt", -1, ckpt="/tmp/bad.ckpt",
                   message="self-test corrupt"),
        "self-test ckpt_corrupt event",
    )
    # Elastic world-size events (shrink/grow governance + the loop's
    # reshard-on-load announcement) and the bench fault block's resize
    # keys — the exact shapes strategies._record_recovery and
    # loop._announce_resize produce.
    problems += validate_stream_item(
        make_event("resize", -1, old_world=4, new_world=2,
                   recover_s=3.2, ckpt="/tmp/drain-step-00000007.ckpt",
                   message="self-test elastic resize"),
        "self-test resize event",
    )
    problems += validate_stream_item(
        make_event("resize_rejected", -1, old_world=4, new_world=0,
                   message="self-test below elastic_min_workers"),
        "self-test resize_rejected event",
    )
    from ray_lightning_tpu.telemetry.schema import validate_bench_fault

    problems += validate_bench_fault(
        {"time_to_recover_s": 1.5, "drain_checkpoint_s": 0.2,
         "backoff_s": None, "resize_time_to_recover_s": 2.5,
         "resize_old_world": 2, "resize_new_world": 1},
        "self-test bench fault block",
    )
    problems += validate_stream_item(
        make_log_item(0, "WARNING", "self.test", "hello"),
        "self-test log",
    )
    rec = FlightRecorder(rank=0, out_dir=tmp, ctx=ctx)
    try:
        raise ValueError("self-test crash")
    except ValueError as err:
        path = rec.record_crash(err)
    if path is None:
        problems.append("self-test bundle: recorder wrote nothing")
    else:
        with open(path) as f:
            problems += validate_flight_bundle(
                json.load(f), "self-test bundle"
            )
    problems += _self_test_host_overhead()
    problems += _self_test_opt_state()
    problems += _self_test_serve()
    problems += _self_test_mpmd()
    problems += _self_test_trace()
    problems += _self_test_programs()
    problems += _self_test_slo_capacity()
    return problems


def _self_test_programs() -> list:
    """Program-ledger producers vs their schema, jax-free: a REAL
    ``ProgramLedger`` fed a record plus a ``diff_signatures``
    attribution must snapshot schema-valid, with the attribution
    naming the changed argument; then negatives (an unknown delta
    kind, a missing attribution, a negative compile wall, an unknown
    row key, a bench block without its overhead A/B) must FAIL."""
    from ray_lightning_tpu.telemetry.program_ledger import (
        ArgSig, ProgramLedger, ProgramRecord, Signature, diff_signatures,
    )

    problems = []
    old = Signature(
        args=(
            ArgSig("state", "PyTreeDef({'p': *})",
                   (("['p']", (8,), "float32"),)),
            ArgSig("batch", "PyTreeDef(*)", (("", (4, 2), "float32"),)),
        ),
        statics=(), donate=(0,),
    )
    # shape delta on state['p']
    new = old._replace(args=(
        old.args[0]._replace(leaves=(("['p']", (16,), "float32"),)),
        old.args[1],
    ))
    diff = diff_signatures(old, new)
    if diff["kind"] != "shape" or diff["argument"] != "state['p']":
        problems.append(
            f"self-test programs: shape delta misattributed ({diff})"
        )
    # dtype delta on batch
    diff = diff_signatures(old, old._replace(args=(
        old.args[0],
        old.args[1]._replace(leaves=(("", (4, 2), "bfloat16"),)),
    )))
    if diff["kind"] != "dtype" or diff["argument"] != "batch":
        problems.append(
            f"self-test programs: dtype delta misattributed ({diff})"
        )
    # structure delta (treedef change on state)
    diff = diff_signatures(old, old._replace(args=(
        old.args[0]._replace(treedef="PyTreeDef({'p': *, 'q': *})"),
        old.args[1],
    )))
    if diff["kind"] != "structure" or diff["argument"] != "state":
        problems.append(
            f"self-test programs: structure delta misattributed ({diff})"
        )
    # donation delta
    diff = diff_signatures(old, old._replace(donate=()))
    if diff["kind"] != "donation":
        problems.append(
            f"self-test programs: donation delta misattributed ({diff})"
        )

    # A real ledger round-trip: record + recompile -> schema-valid snap.
    reg = ProgramLedger()
    reg.record_program(
        ProgramRecord(site="train/step", variant=0,
                      signature="state:f32[8]|batch:f32[4,2]",
                      compile_s=0.25, backend="cpu", ncalls=3,
                      flops=1.0e6, bytes_accessed=2.0e6,
                      argument_bytes=64, output_bytes=32,
                      temp_bytes=16),
        old,
    )
    # The forensics warning is real-recompile UX; a self-test-induced
    # "recompile at train/step" line in format.sh output is a false
    # alarm for whoever reads the gate log.
    import logging

    ledger_log = logging.getLogger("ray_lightning_tpu.program_ledger")
    ledger_log.disabled = True
    try:
        reg.record_recompile(
            "train/step", diff_signatures(old, new), variant=1
        )
    finally:
        ledger_log.disabled = False
    snap = reg.snapshot()
    problems += validate_program_snapshot(snap, "self-test programs snap")
    rec = snap["recompiles"][0]
    if rec["argument"] != "state['p']" or rec["kind"] != "shape":
        problems.append(
            "self-test programs: ledger recompile record lost the "
            f"attribution ({rec})"
        )

    # Negatives: a drifted producer must not validate.
    if not validate_recompile_record(
        {**rec, "kind": "weather"}
    ):
        problems.append(
            "self-test programs: validator accepted an unknown delta "
            "kind"
        )
    if not validate_recompile_record({**rec, "argument": ""}):
        problems.append(
            "self-test programs: validator accepted an empty argument "
            "attribution"
        )
    bad = json_roundtrip(snap)
    bad["programs"][0]["compile_s"] = -1.0
    if not validate_program_snapshot(bad):
        problems.append(
            "self-test programs: validator accepted a negative compile "
            "wall"
        )
    bad = json_roundtrip(snap)
    bad["programs"][0]["flavor"] = "vanilla"
    if not validate_program_snapshot(bad):
        problems.append(
            "self-test programs: validator accepted an unknown row key"
        )

    block = {
        "n_programs": 2, "compile_time_total_s": 1.5,
        "recompile_events": 1, "ledger_overhead_pct": 0.02,
        "rows": snap["programs"], "hbm": {"sites": {}},
        "mfu_basis": "measured",
    }
    problems += validate_bench_programs(block, "self-test bench programs")
    if not validate_bench_programs(
        {k: v for k, v in block.items() if k != "ledger_overhead_pct"}
    ):
        problems.append(
            "self-test bench programs: validator accepted a block "
            "missing the overhead A/B"
        )
    if not validate_bench_programs({**block, "mfu_basis": "vibes"}):
        problems.append(
            "self-test bench programs: validator accepted an unknown "
            "mfu basis"
        )
    return problems


def _self_test_trace() -> list:
    """Distributed-tracing producers vs their schema: the propagate
    inject/extract envelope on REAL wire frames (request_fields, a
    handoff item, a QueueChannel mpmd_xfer), a wall-clock tracer's
    ``start_remote`` export, the trace_collect stitcher's Chrome
    output, and the bench trace block — plus negatives (empty ids,
    coverage outside [0, 1], a phase summary missing its percentiles,
    both payload forms)."""
    import time

    from ray_lightning_tpu.mpmd.transfer import QueueChannel
    from ray_lightning_tpu.serve.dist.handoff import (
        make_handoff_item, request_fields,
    )
    from ray_lightning_tpu.telemetry import trace_collect
    from ray_lightning_tpu.telemetry.propagate import (
        child_context, extract, root_context,
    )
    from ray_lightning_tpu.telemetry.spans import SpanTracer

    problems = []
    root = root_context("rid42")
    if root.span_id != "rid42.root":
        problems.append("self-test trace: root span id not derived")
    req = request_fields(
        "rid42", [1, 2, 3], 8, reply=("127.0.0.1", 9), sample_seed=1,
        trace=root,
    )
    problems += validate_serve_request(req, "self-test traced request")
    problems += validate_trace_context(
        req.get("trace"), "self-test trace envelope"
    )
    if extract(req) != root:
        problems.append("self-test trace: inject/extract not a roundtrip")
    child = child_context(root)
    handoff = make_handoff_item(req, bucket=16, data=b"\x00",
                                trace=child)
    problems += validate_serve_kv_handoff(
        handoff, "self-test traced handoff"
    )
    if not validate_trace_context({"trace_id": "", "span_id": "x"}):
        problems.append(
            "self-test trace: validator accepted an empty trace_id"
        )
    if not validate_serve_request(
        {**req, "trace": {"span_id": "x"}}
    ):
        problems.append(
            "self-test trace: request validator accepted a trace "
            "without trace_id"
        )

    # A traced mpmd_xfer through the REAL channel encoder.
    sent = []

    class _StubHandle:
        def put(self, item):
            sent.append(item)

        def close(self):
            pass

    chan = QueueChannel.__new__(QueueChannel)
    chan._handle = _StubHandle()
    chan._store = None
    chan._shm_threshold = 1 << 30
    chan._codec = None
    chan.bytes_sent = 0
    chan.shm_sends = 0
    chan.send("act", 0, 1, {"x": [1.0]}, chunk=0, trace=root)
    problems += validate_mpmd_xfer(sent[0], "self-test traced xfer")
    if "trace" not in sent[0]:
        problems.append("self-test trace: channel dropped the envelope")

    # Remote-parented spans through the REAL tracer + stitcher.
    tracer = SpanTracer(enabled=True, rank=0, clock=time.time)
    with tracer.start_remote(root, "prefill_compute", rid="rid42") as sp:
        if sp.ctx is None or sp.ctx.parent_span_id != root.span_id:
            problems.append(
                "self-test trace: start_remote did not parent to the "
                "remote context"
            )
    with tempfile.TemporaryDirectory(prefix="rlt_trace_") as tmp:
        tracer.export_jsonl(os.path.join(tmp, "trace-worker.jsonl"))
        with open(os.path.join(tmp, "trace-worker.jsonl")) as f:
            problems += validate_span_jsonl(
                f.readlines(), "self-test trace jsonl"
            )
        spans = trace_collect.load_trace_dir(tmp)
        problems += validate_chrome_trace(
            trace_collect.stitch_chrome(spans), "self-test stitched"
        )

    block = {
        "coverage": 1.0, "requests": 24, "overhead_pct": 0.4,
        "complete_chains": 24, "spans": 480,
        "traced_requests_per_sec": 8.1,
        "baseline_requests_per_sec": 8.2,
        "replicas": 2, "prefill_workers": 1,
        "phases": {
            "queue_wait": {"n": 24, "p50_ms": 0.2, "p95_ms": 1.1},
            "prefill_compute": {"n": 24, "p50_ms": 9.0, "p95_ms": 14.0},
        },
    }
    problems += validate_bench_trace(block, "self-test bench trace")
    if not validate_bench_trace({**block, "coverage": 1.2}):
        problems.append(
            "self-test bench trace: validator accepted coverage > 1"
        )
    if not validate_bench_trace({"coverage": 1.0}):
        problems.append(
            "self-test bench trace: validator accepted a block missing "
            "the phase map"
        )
    if not validate_bench_trace(
        {**block, "phases": {"queue_wait": {"n": 1, "p50_ms": 0.1}}}
    ):
        problems.append(
            "self-test bench trace: validator accepted a phase summary "
            "missing p95"
        )
    return problems


def _self_test_opt_state() -> list:
    """The HBM-diet bench blocks (opt_state + residual_policy): the
    shapes bench.py emits must pass, drifted producers must NOT.  The
    analytic byte counts here are hand-computed miniatures of the
    models/optim.py / models/gpt.py accounting, so a validator change
    that loosens the contract shows up as an accepted negative."""
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_opt_state,
        validate_bench_residual_policy,
    )

    problems = validate_bench_opt_state(
        {
            "dtype": "int8", "block_size": 128,
            "bytes_f32": 3829760, "bytes_int8": 1008640,
            "bytes_active": 1008640, "hbm_ratio": 3.797,
            "loss_rel_diff_vs_f32": 1.3e-6,
            "tokens_per_sec": 1234.5, "vs_baseline": 1.01,
            "update_sharding": "off",
        },
        "self-test opt_state",
    )
    # Nullable measured arms (probe best-effort) are a legal block.
    problems += validate_bench_opt_state(
        {
            "dtype": "float32", "block_size": 128,
            "bytes_f32": 100.0, "bytes_int8": 26.0,
            "bytes_active": 100.0, "hbm_ratio": 3.85,
            "loss_rel_diff_vs_f32": None, "tokens_per_sec": None,
        },
        "self-test opt_state nulls",
    )
    if not validate_bench_opt_state({"dtype": "int8"}):
        problems.append(
            "self-test opt_state: validator accepted a block missing "
            "the byte accounting"
        )
    if not validate_bench_opt_state(
        {"dtype": "int8", "block_size": 0, "bytes_f32": 1,
         "bytes_int8": 1, "bytes_active": 1, "hbm_ratio": 1.0}
    ):
        problems.append(
            "self-test opt_state: validator accepted block_size=0"
        )
    problems += validate_bench_residual_policy(
        {
            "policy": "bf16-resid", "baseline_policy": "dots+flash",
            "residual_bytes_per_step": 44564480,
            "baseline_residual_bytes_per_step": 59244544,
            "bytes_saved_pct": 24.8,
            "tokens_per_sec": None, "vs_baseline": None,
            "loss_rel_diff_vs_baseline": 1.6e-5,
        },
        "self-test residual_policy",
    )
    if not validate_bench_residual_policy({"policy": "dots"}):
        problems.append(
            "self-test residual_policy: validator accepted a block "
            "missing the byte accounting"
        )
    return problems


def _self_test_mpmd() -> list:
    """MPMD-plane producers vs their schema: a REAL transfer frame (the
    QueueChannel encoder feeding a stub queue), the per-step stage beat,
    the live snapshot, and the bench block — plus negative cases."""
    from ray_lightning_tpu.mpmd.transfer import QueueChannel

    sent = []

    class _StubHandle:
        def put(self, item):
            sent.append(item)

        def close(self):
            pass

    chan = QueueChannel.__new__(QueueChannel)
    chan._handle = _StubHandle()
    chan._store = None
    chan._shm_threshold = 1 << 30
    chan._codec = None
    chan.bytes_sent = 0
    chan.shm_sends = 0
    chan.send("act", 3, 1, {"x": [1.0, 2.0]}, chunk=1)
    problems = validate_mpmd_xfer(sent[0], "self-test mpmd xfer")

    # A codec-bearing frame through the REAL encoder: the "enc" stamp
    # must validate (round 25's quantized-wire accounting).
    import numpy as _np

    from ray_lightning_tpu.mpmd.transfer import WireCodec, WireDtypeConfig

    chan._codec = WireCodec(WireDtypeConfig.coerce("act:bf16,grad:int8"))
    chan.send("grad", 3, 1, {"g": _np.ones(8, _np.float32)}, chunk=1)
    problems += validate_mpmd_xfer(sent[1], "self-test mpmd xfer enc")
    if sent[1].get("enc") != "act:bf16,grad:int8":
        problems.append(
            "self-test mpmd xfer enc: codec frame missing its enc stamp"
        )

    beat = {
        "type": "mpmd_stage", "stage": 1, "step": 4,
        "bubble_fraction": 0.12, "stage_occupancy": 0.88,
        "busy_s": 0.4, "blocked_s": 0.05, "loss": 4.2,
    }
    problems += validate_stream_item(beat, "self-test mpmd beat")
    problems += validate_mpmd_snapshot(
        {
            "schedule": "1f1b", "interleave": 2, "n_micro": 8,
            "n_stages": 2, "stages": [beat],
        },
        "self-test mpmd snapshot",
    )
    problems += validate_bench_mpmd(
        {
            "schedule": "1f1b", "n_stages": 2, "n_micro": 8,
            "interleave": 2, "bubble_fraction": 0.08,
            "gpipe_bubble_fraction": 0.13, "stage_occupancy": 0.9,
            "stage_skew_ms": 1.2, "tokens_per_sec": 1000.0,
            "single_mesh_tokens_per_sec": 1100.0, "vs_single_mesh": 0.91,
            "loss_parity_max_diff": 1e-6,
            "op_costs_ms": {"FWD": 1.2, "BWD": 4.0, "SEND": 0.5},
        },
        "self-test bench mpmd",
    )
    if not validate_mpmd_xfer({**sent[0], "shm": "/dev/shm/x"}):
        problems.append(
            "self-test mpmd xfer: validator accepted data AND shm"
        )
    if not validate_bench_mpmd({"schedule": "1f1b"}):
        problems.append(
            "self-test bench mpmd: validator accepted a block missing "
            "the pipeline shape"
        )
    if not validate_stream_item(
            {**beat, "bubble_fraction": 1.5}, "neg"):
        problems.append(
            "self-test mpmd beat: validator accepted bubble > 1"
        )
    problems += _self_test_comm_overlap()
    return problems


def _self_test_comm_overlap() -> list:
    """The bench comm_overlap block (round 25) — a representative
    passing block, then negatives (wire volume drifting under overlap,
    an hlo_gate claim without interleaved collectives, a block missing
    its A/B identification)."""
    good = {
        "segments": 2, "mode": "int8_ef", "devices": 8,
        "loss_rel_diff": 0.002, "loss_step_end": 6.27,
        "loss_overlap": 6.28,
        "grad_sync_bytes_step_end": 60160.0,
        "grad_sync_bytes_overlap": 60416.0,
        "bytes_ratio": 1.0043,
        "dispatches_per_opt_step_step_end": 1.0,
        "dispatches_per_opt_step_overlap": 1.0,
        "recompiles_step_end": 0, "recompiles_overlap": 0,
        "collectives_before_last_dot_step_end": 0,
        "collectives_before_last_dot_overlap": 54,
        "hlo_gate": True,
        "mpmd_wire_enc": "act:bf16,grad:int8",
        "mpmd_wire_ratio": 1.99,
        "mpmd_loss_rel_diff": 0.0001,
    }
    problems = validate_bench_comm_overlap(
        good, "self-test bench comm_overlap"
    )
    if not validate_bench_comm_overlap({**good, "bytes_ratio": 1.5}):
        problems.append(
            "self-test bench comm_overlap: validator accepted a 1.5x "
            "wire-volume drift"
        )
    if not validate_bench_comm_overlap(
            {**good, "collectives_before_last_dot_overlap": 0}):
        problems.append(
            "self-test bench comm_overlap: validator accepted hlo_gate "
            "without interleaved collectives"
        )
    if not validate_bench_comm_overlap({"segments": 2}):
        problems.append(
            "self-test bench comm_overlap: validator accepted a block "
            "missing its A/B identification"
        )
    if not validate_bench_comm_overlap({**good, "mpmd_wire_ratio": 0.5}):
        problems.append(
            "self-test bench comm_overlap: validator accepted a codec "
            "that inflated the wire"
        )
    return problems


def _self_test_serve() -> list:
    """Serving-plane producers vs their schema: the REAL ServeStats
    engine's snapshot, the client's wire items, and the bench_serve
    block shape — plus negative cases so a drifted validator can't
    silently accept anything."""
    from ray_lightning_tpu.serve.metrics import ServeStats

    stats = ServeStats()
    stats.bump("submitted")
    stats.note_admitted(0.01)
    stats.note_first_token(0.05)
    stats.note_token_latency(0.004, n_tokens=3)
    stats.note_completed(0.2)
    stats.set_gauges(queue_depth=0, slots_active=1, num_slots=8,
                     blocks_free=30, blocks_live=2, num_blocks=33)
    problems = validate_serve_snapshot(
        stats.snapshot(), "self-test serve snapshot"
    )
    problems += validate_serve_request(
        {
            "type": "serve_request", "rid": "abc", "prompt": [1, 2],
            "max_new_tokens": 4, "temperature": 0.0,
            "eos_token_id": None, "deadline_s": 0.5,
            "reply": ["127.0.0.1", 12345],
        },
        "self-test serve request",
    )
    problems += validate_serve_reply(
        {"type": "serve_token", "rid": "abc", "index": 0, "token": 7},
        "self-test serve token",
    )
    problems += validate_serve_reply(
        {"type": "serve_done", "rid": "abc", "status": "finished",
         "reason": "length", "tokens": [7, 9]},
        "self-test serve done",
    )
    problems += validate_bench_serve(
        {
            "requests_per_sec": 12.5,
            "tokens_per_sec": 200.0,
            "p50_token_latency_ms": 8.0,
            "p99_token_latency_ms": 21.0,
            "p50_ttft_ms": 30.0,
            "p99_ttft_ms": 80.0,
            "recompiles_steady_state": 0,
            "continuous_vs_sequential": 2.1,
            "sequential_requests_per_sec": 6.0,
            "num_slots": 8, "block_size": 16, "num_blocks": 33,
            "completed": 64, "preempted": 0, "rejected": 0, "expired": 0,
            "rate_sweep": [{
                "offered_rps": 4.0, "requests_per_sec": 3.9,
                "p50_token_latency_ms": 9.0,
                "p99_token_latency_ms": 30.0, "completed": 16,
            }],
        },
        "self-test bench serve",
    )
    if not validate_bench_serve({"requests_per_sec": 1.0}):
        problems.append(
            "self-test bench serve: validator accepted a block missing "
            "the latency percentiles"
        )
    if not validate_serve_reply({"type": "serve_weird", "rid": "x"}):
        problems.append(
            "self-test serve reply: validator accepted an unknown type"
        )
    problems += _self_test_spec_decode(stats)
    problems += _self_test_serve_disagg()
    problems += _self_test_serve_chaos()
    problems += _self_test_multi_lora()
    problems += _self_test_prefix_cache()
    return problems


def _self_test_serve_chaos() -> list:
    """Serving-plane resilience producers vs their schema (ISSUE 19):
    a REAL migration frame (the serve/dist frame builder carrying KV
    payload + scheduler position), the typed shed reply, the hedged
    resubmit / priority request fields, the router snapshot's brownout
    level, and the bench serve_chaos block — plus negatives (a
    position invariant that doesn't add up, an empty migration, a
    brownout level off the ladder, a chaos block missing its parity
    pin)."""
    from ray_lightning_tpu.serve.dist.handoff import (
        make_migration_item, request_fields,
    )
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_serve_chaos, validate_serve_migration,
    )

    req = request_fields(
        "abc", [1, 2, 3], 8, reply=("127.0.0.1", 12345), sample_seed=7,
        temperature=0.7, priority=1,
    )
    problems = validate_serve_request(req, "self-test priority request")
    item = make_migration_item(
        req, generated=[5, 6], cur_token=6, seq_len=4, data=b"\x00kv",
    )
    problems += validate_serve_migration(item, "self-test migration")
    # No json_roundtrip here: migration frames carry a raw-bytes KV
    # payload (they ride the pickled beat lane, never JSON).
    if not validate_serve_migration({**item, "seq_len": 99}):
        problems.append(
            "self-test migration: validator accepted a scheduler "
            "position that doesn't match prompt + generated"
        )
    if not validate_serve_migration({**item, "generated": []}):
        problems.append(
            "self-test migration: validator accepted an empty stream "
            "(nothing decoded = nothing worth migrating)"
        )
    seedless = {
        **item,
        "req": {k: v for k, v in req.items() if k != "sample_seed"},
    }
    if not validate_serve_migration(seedless):
        problems.append(
            "self-test migration: validator accepted a frame without "
            "the fleet sample_seed (parity on the survivor needs it)"
        )
    problems += validate_serve_request(
        {**req, "hedge": True}, "self-test hedged resubmit"
    )
    problems += validate_serve_reply(
        {"type": "serve_done", "rid": "abc", "status": "shed",
         "reason": "brownout", "tokens": []},
        "self-test shed reply",
    )
    if not validate_router_snapshot(
        {"replicas": [], "prefill_workers": [], "inflight": 0,
         "counters": {}, "brownout_level": 7}
    ):
        problems.append(
            "self-test router snapshot: validator accepted a brownout "
            "level off the ladder"
        )
    block = {
        "migrations": 2, "migration_ttr_s": 0.4, "failover_ttr_s": 1.3,
        "migration_vs_failover": 3.2, "lost_requests": 0,
        "migration_re_emitted_tokens": 0, "parity": True,
        "recompiles_steady_state": 0, "failover_re_emitted_tokens": 9,
        "hedges": 1, "hedge_cancels": 1, "shed": 2,
        "brownout_level_max": 3,
    }
    problems += validate_bench_serve_chaos(
        block, "self-test bench serve_chaos"
    )
    for key in ("parity", "migration_re_emitted_tokens"):
        broken = {k: v for k, v in block.items() if k != key}
        if not validate_bench_serve_chaos(broken):
            problems.append(
                f"self-test serve_chaos: validator accepted a block "
                f"missing {key!r}"
            )
    if not validate_bench_serve_chaos(
        {**block, "brownout_level_max": 9}
    ):
        problems.append(
            "self-test serve_chaos: validator accepted a brownout "
            "level off the ladder"
        )
    return problems


def _self_test_prefix_cache() -> list:
    """Prefix-cache / chunked-prefill producers vs their schema: a REAL
    ServeStats snapshot carrying the engine's set_prefix block, the
    bench prefix_cache and chunked_prefill blocks, and the router
    replica hit-rate gauge — plus negatives (hit_rate outside [0, 1],
    hits > lookups, a bench block missing its baseline recompile pin,
    a chunked block with zero chunks)."""
    from ray_lightning_tpu.serve.metrics import ServeStats

    stats = ServeStats()
    stats.bump("prefills")
    stats.bump("prefill_chunks", 3)
    stats.set_gauges(queue_depth=0, prefix_cache_hit_rate=0.5,
                     prefix_cached_blocks=6)
    stats.set_prefix(hit_rate=0.5, lookups=4, hits=2, blocks_claimed=4,
                     blocks_inserted=8, blocks_evicted=0,
                     cached_blocks=6)
    snap = stats.snapshot()
    problems = validate_serve_snapshot(snap, "self-test prefix snapshot")
    bad = json_roundtrip(snap)
    bad["prefix"]["hit_rate"] = 1.5
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test prefix snapshot: validator accepted "
            "hit_rate > 1"
        )
    bad = json_roundtrip(snap)
    bad["prefix"]["hits"] = bad["prefix"]["lookups"] + 1
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test prefix snapshot: validator accepted "
            "hits > lookups"
        )
    bad = json_roundtrip(snap)
    del bad["prefix"]["cached_blocks"]
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test prefix snapshot: validator accepted a prefix "
            "block missing its occupancy counter"
        )

    block = {
        "prefix_share": 0.6, "requests": 16, "hit_rate": 0.44,
        "blocks_claimed": 24, "ttft_p50_ms": 12.0,
        "baseline_ttft_p50_ms": 30.0, "ttft_speedup": 2.5,
        "tokens_per_sec": 240.0, "baseline_tokens_per_sec": 200.0,
        "recompiles_steady_state": 0,
        "baseline_recompiles_steady_state": 0,
        "token_parity": True, "blocks_inserted": 40,
        "cached_blocks": 36, "prefill_chunks": 16,
        "max_new_tokens": 8,
    }
    problems += validate_bench_prefix_cache(
        block, "self-test bench prefix_cache"
    )
    if not validate_bench_prefix_cache(
        {k: v for k, v in block.items()
         if k != "baseline_recompiles_steady_state"}
    ):
        problems.append(
            "self-test prefix_cache: validator accepted a block "
            "missing the baseline recompile pin"
        )
    if not validate_bench_prefix_cache({**block, "hit_rate": -0.1}):
        problems.append(
            "self-test prefix_cache: validator accepted a negative "
            "hit_rate"
        )
    if not validate_bench_prefix_cache({**block, "prefix_share": 1.2}):
        problems.append(
            "self-test prefix_cache: validator accepted "
            "prefix_share > 1"
        )

    chunked = {
        "prompt_len": 4096, "chunk_width": 512, "chunks": 8,
        "resident_max_stall_ticks": 1, "recompiles_steady_state": 0,
        "ttft_ms": 180.0, "resident_requests": 2,
        "tokens_per_sec": None,
    }
    problems += validate_bench_chunked_prefill(
        chunked, "self-test bench chunked_prefill"
    )
    if not validate_bench_chunked_prefill({**chunked, "chunks": 0}):
        problems.append(
            "self-test chunked_prefill: validator accepted zero chunks"
        )
    if not validate_bench_chunked_prefill(
        {k: v for k, v in chunked.items()
         if k != "resident_max_stall_ticks"}
    ):
        problems.append(
            "self-test chunked_prefill: validator accepted a block "
            "missing the no-stall pin"
        )
    return problems


def _self_test_multi_lora() -> list:
    """Multi-tenant LoRA producers vs their schema: a REAL per-tenant
    ServeStats snapshot (note_adapter feeds the ``adapters`` block and
    the fairness gauge), an adapter-bearing wire request
    (request_fields), a REAL hot-load frame (make_adapter_load_item),
    and the bench multi_lora block — plus negatives (both payload
    forms, a non-string adapter field, a fairness spread outside
    [0, 1], per-tenant accounting with a dropped counter, recompile
    pins missing)."""
    from ray_lightning_tpu.serve.dist.handoff import (
        make_adapter_load_item, request_fields,
    )
    from ray_lightning_tpu.serve.metrics import ServeStats
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_multi_lora, validate_serve_adapter_load,
    )

    stats = ServeStats()
    stats.bump("submitted", 2)
    stats.note_adapter("tenant0", tokens=16, completed=1)
    stats.note_adapter("tenant1", tokens=16, completed=1)
    stats.set_gauges(queue_depth=0, lora_adapters_loaded=2,
                     lora_slots_free=6, lora_fairness_spread=1.0)
    snap = stats.snapshot()
    problems = validate_serve_snapshot(snap, "self-test lora snapshot")
    bad = json_roundtrip(snap)
    bad["gauges"]["lora_fairness_spread"] = 1.5
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test lora snapshot: validator accepted a fairness "
            "spread > 1"
        )
    bad = json_roundtrip(snap)
    del bad["adapters"]["tenant0"]["completed"]
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test lora snapshot: validator accepted a tenant "
            "entry missing its completion counter"
        )

    req = request_fields(
        "abc", [1, 2, 3], 8, reply=("127.0.0.1", 12345), sample_seed=3,
        adapter="tenant0",
    )
    problems += validate_serve_request(req, "self-test lora request")
    if not validate_serve_request({**req, "adapter": 7}):
        problems.append(
            "self-test lora request: validator accepted a non-string "
            "adapter"
        )

    load = make_adapter_load_item("tenant0", 8, data=b"\x00factors")
    problems += validate_serve_adapter_load(load, "self-test lora load")
    problems += validate_serve_adapter_load(
        make_adapter_load_item("tenant0", 8, shm="/dev/shm/rlt-kv-1"),
        "self-test lora load shm",
    )
    if not validate_serve_adapter_load({**load, "shm": "/dev/shm/x"}):
        problems.append(
            "self-test lora load: validator accepted data AND shm"
        )
    if not validate_serve_adapter_load(
        {**{k: v for k, v in load.items() if k != "data"},
         "shm": "/x", "rank": 0}
    ):
        problems.append(
            "self-test lora load: validator accepted rank 0"
        )

    block = {
        "adapters": 8, "rank": 8, "requests": 16, "max_new_tokens": 16,
        "tokens_per_sec": 300.0, "baseline_tokens_per_sec": 90.0,
        "vs_baseline": 3.33, "fairness_spread": 1.0,
        "recompiles_steady_state": 0,
        "baseline_recompiles_steady_state": 0,
        "greedy_parity": True, "hot_adds": 2, "pool_loads": 8,
        "bgmv_impl": "xla", "completed": 16,
    }
    problems += validate_bench_multi_lora(
        block, "self-test bench multi_lora"
    )
    if not validate_bench_multi_lora(
        {k: v for k, v in block.items()
         if k != "baseline_recompiles_steady_state"}
    ):
        problems.append(
            "self-test multi_lora: validator accepted a block missing "
            "the baseline recompile pin"
        )
    if not validate_bench_multi_lora({**block, "fairness_spread": -0.1}):
        problems.append(
            "self-test multi_lora: validator accepted a negative "
            "fairness spread"
        )
    if not validate_bench_multi_lora({**block, "bgmv_impl": "magic"}):
        problems.append(
            "self-test multi_lora: validator accepted an unknown BGMV "
            "arm"
        )
    return problems


def _self_test_serve_disagg() -> list:
    """Disaggregated-serving producers vs their schema: a REAL handoff
    envelope (the serve/dist frame builders), a REAL router snapshot
    (a Router with stub members fed the real hello/beat items), and
    the bench serve_disagg block — plus negatives (a handoff with both
    payload forms, one without the fleet seed, a chaos block whose loss
    accounting doesn't add up)."""
    from ray_lightning_tpu.serve.dist.handoff import (
        make_beat_item, make_handoff_item, make_hello_item,
        request_fields,
    )
    from ray_lightning_tpu.serve.dist.router import Router

    req = request_fields(
        "abc", [1, 2, 3], 8, reply=("127.0.0.1", 12345), sample_seed=7,
        temperature=0.7, top_k=8, spec=2,
    )
    handoff = make_handoff_item(req, bucket=16, data=b"\x00payload")
    problems = validate_serve_kv_handoff(handoff, "self-test handoff")
    problems += validate_serve_kv_handoff(
        make_handoff_item(req, bucket=16, shm="/dev/shm/rlt-kv-1-abc"),
        "self-test handoff shm",
    )
    if not validate_serve_kv_handoff(
        {**handoff, "shm": "/dev/shm/x"}
    ):
        problems.append(
            "self-test handoff: validator accepted data AND shm"
        )
    seedless = dict(handoff)
    seedless["req"] = {k: v for k, v in req.items()
                      if k != "sample_seed"}
    if not validate_serve_kv_handoff(seedless):
        problems.append(
            "self-test handoff: validator accepted a handoff without "
            "the fleet sample_seed"
        )

    class _StubHandle:
        def __init__(self, member_id):
            self.id = member_id

        def is_alive(self):
            return True

        def kill(self):
            pass

    router = Router(lost_after_s=60.0)
    try:
        router.add_replica(_StubHandle("r0"))
        router.add_prefill(_StubHandle("p0"))
        # Real wire: hello + beat ride the beat queue's TCP loopback
        # exactly as fleet members send them.
        beat_handle = router.beat_handle
        beat_handle.put(make_hello_item(
            "decode", "r0", ("127.0.0.1", 1), num_slots=8, max_queue=64,
            spec_k=4, max_prompt_len=64, max_model_len=128,
            block_size=16, max_adapters=4,
        ))
        beat_handle.put(make_hello_item(
            "prefill", "p0", ("127.0.0.1", 2), max_prompt_len=64,
            max_model_len=128, block_size=16,
        ))
        beat_handle.put(make_beat_item(
            "decode", "r0", done=[("x", "finished")],
            snapshot={"ts": 0.0, "counters": {}, "latency": {},
                      "gauges": {"slots_active": 1, "num_slots": 8,
                                 "blocks_free": 20, "num_blocks": 33,
                                 "queue_depth": 0,
                                 "spec_acceptance_rate": 0.9,
                                 "prefix_cache_hit_rate": 0.4}},
            recompiles=12,
            adapters=["tenant0", "tenant1"],
        ))
        router.poll()
        beat_handle.close()
        snap = router.snapshot()
        problems += validate_router_snapshot(
            snap, "self-test router snapshot"
        )
        bad = json_roundtrip(snap)
        bad["replicas"][0]["inflight"] = -1
        if not validate_router_snapshot(bad):
            problems.append(
                "self-test router snapshot: validator accepted a "
                "negative inflight"
            )
        bad = json_roundtrip(snap)
        bad["replicas"][0]["prefix_cache_hit_rate"] = 1.5
        if not validate_router_snapshot(bad):
            problems.append(
                "self-test router snapshot: validator accepted a "
                "replica prefix hit rate > 1"
            )
    finally:
        router.stop()

    block = {
        "replicas": 2, "prefill_workers": 1, "requests": 24,
        "requests_per_sec": 3.5, "tokens_per_sec": 56.0,
        "monolith_requests_per_sec": 4.0, "vs_monolith": 0.875,
        "kv_imports": 24, "prefill_dispatches": 24,
        "p50_ttft_ms": 40.0, "p99_ttft_ms": 120.0,
        "recompiles_steady_state": 0,
        "chaos": {
            "killed_replica": "r0", "submitted": 24, "completed": 24,
            "lost_requests": 0, "failed_over_requests": 3,
            "failover_detect_s": 0.6, "re_emitted_tokens": 11,
            "survivor_recompiles_steady_state": 0, "offered_rps": 4.0,
        },
    }
    problems += validate_bench_serve_disagg(
        block, "self-test bench serve_disagg"
    )
    if not validate_bench_serve_disagg({"replicas": 2}):
        problems.append(
            "self-test serve_disagg: validator accepted a block "
            "missing the headline"
        )
    bad_chaos = json_roundtrip(block)
    bad_chaos["chaos"]["completed"] = 30
    if not validate_bench_serve_disagg(bad_chaos):
        problems.append(
            "self-test serve_disagg: validator accepted "
            "completed + lost > submitted"
        )
    return problems


def _self_test_slo_capacity() -> list:
    """SLO & capacity plane producers vs their schema (ISSUE 18): a
    REAL TimeSeriesStore's points/JSONL dump, a REAL SloEvaluator's
    fired alert, and a REAL CapacityOracle snapshot fed from real
    ServeStats snapshots — plus negatives (unknown kind, samples on a
    non-hist point, a detail-less alert, target outside (0,1),
    utilization > 1, a bench block missing its cold-arm pin)."""
    from ray_lightning_tpu.serve.capacity import (
        CapacityOracle, aggregate_fleet,
    )
    from ray_lightning_tpu.serve.metrics import ServeStats
    from ray_lightning_tpu.telemetry.slo import (
        SloEvaluator, default_serve_slos,
    )
    from ray_lightning_tpu.telemetry.timeseries import TimeSeriesStore

    problems = []
    clock = [1000.0]
    store = TimeSeriesStore(interval_s=1.0, capacity=600,
                            clock=lambda: clock[0])
    # 200 one-second bins: half the admissions rejected (burn 50x the
    # 0.99 budget — every window pair must fire), a busy gauge and a
    # latency hist so every kind appears in the dump.
    for i in range(200):
        ts = 1000.0 + i
        store.observe("submitted", 10 * i, kind="counter", ts=ts)
        store.observe("rejected", 5 * i, kind="counter", ts=ts)
        store.observe("queue_wait_p50_ms", 5.0 + i % 3, kind="gauge",
                      ts=ts)
        store.observe("token_ms", 4.0 + (i % 5), kind="hist", ts=ts)
    pts = store.points(window_s=30.0)
    if not pts:
        problems.append("self-test timeseries: no points in window")
    for point in pts:
        problems += validate_timeseries_point(
            point, "self-test timeseries point"
        )
    with tempfile.TemporaryDirectory(prefix="rlt_ts_") as tmp:
        path = os.path.join(tmp, "ts.jsonl")
        n = store.dump_jsonl(path, window_s=30.0)
        with open(path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        if len(lines) != n:
            problems.append(
                f"self-test timeseries: dump_jsonl wrote {len(lines)} "
                f"lines, reported {n}"
            )
        for doc in lines:
            problems += validate_timeseries_point(
                doc, "self-test timeseries dump"
            )
    good = json_roundtrip(pts[0])
    if not validate_timeseries_point({**good, "kind": "bogus"}):
        problems.append(
            "self-test timeseries: validator accepted an unknown kind"
        )
    if not validate_timeseries_point({**good, "spurious": 1}):
        problems.append(
            "self-test timeseries: validator accepted an unknown key"
        )
    gauge_pt = next(
        (p for p in pts if p["kind"] == "gauge"), None
    )
    if gauge_pt is not None and not validate_timeseries_point(
        {**json_roundtrip(gauge_pt), "n": 4}
    ):
        problems.append(
            "self-test timeseries: validator accepted a sample count "
            "on a non-hist point"
        )

    # The evaluator over the same store: 50% rejections must fire the
    # availability SLO with a schema-valid alert on the event plane.
    emitted = []
    evaluator = SloEvaluator(store, default_serve_slos(),
                             clock=lambda: clock[0],
                             emit=emitted.append)
    alerts = evaluator.evaluate()
    if not alerts or not emitted:
        problems.append(
            "self-test slo: 50% rejection rate did not fire the "
            "availability alert"
        )
    for alert in alerts:
        problems += validate_slo_alert(alert, "self-test slo alert")
        problems += validate_stream_item(alert, "self-test slo event")
    if evaluator.evaluate():
        problems.append(
            "self-test slo: still-firing spec re-alerted without "
            "re-arming (dedup broken)"
        )
    if alerts:
        bad = json_roundtrip(alerts[0])
        del bad["detail"]
        if not validate_slo_alert(bad):
            problems.append(
                "self-test slo: validator accepted a detail-less alert"
            )
        bad = json_roundtrip(alerts[0])
        bad["detail"]["target"] = 1.5
        if not validate_slo_alert(bad):
            problems.append(
                "self-test slo: validator accepted target outside (0,1)"
            )
        bad = json_roundtrip(alerts[0])
        bad["detail"]["fast_window_s"] = bad["detail"]["slow_window_s"]
        if not validate_slo_alert(bad):
            problems.append(
                "self-test slo: validator accepted fast >= slow window"
            )

    # The oracle fed from REAL ServeStats snapshots: stable busy slots
    # and a draining KV pool give a full capacity_snapshot.
    oracle = CapacityOracle(interval_s=1.0, window_s=30.0,
                            clock=lambda: clock[0])
    stats = ServeStats()
    stats.set_gauges(queue_depth=2, slots_active=4, num_slots=8,
                     blocks_free=100, num_blocks=200)
    for i in range(40):
        stats.bump("tokens_out", 20)
        stats.bump("submitted", 2)
        stats.set_gauges(queue_depth=2, slots_active=4, num_slots=8,
                         blocks_free=100 - 2 * i, num_blocks=200)
        oracle.observe(stats.snapshot(), recompiles=0, ts=1000.0 + i)
    clock[0] = 1040.0
    snap = oracle.snapshot()
    problems += validate_capacity_snapshot(
        snap, "self-test capacity snapshot"
    )
    if not snap.get("capacity_tokens_per_s"):
        problems.append(
            "self-test capacity: oracle measured no ceiling from a "
            "steady 20 tok/s @ 4/8 slots feed"
        )
    if snap.get("kv_exhaustion_eta_s") is None:
        problems.append(
            "self-test capacity: a linearly draining KV pool produced "
            "no exhaustion ETA"
        )
    if oracle.predict_saturation_rps(16) is None:
        problems.append(
            "self-test capacity: no saturation prediction despite a "
            "measured service rate"
        )
    bad = json_roundtrip(snap)
    bad["utilization"] = 1.5
    if not validate_capacity_snapshot(bad):
        problems.append(
            "self-test capacity: validator accepted utilization > 1"
        )
    bad = json_roundtrip(snap)
    del bad["headroom_tokens_per_s"]
    if not validate_capacity_snapshot(bad):
        problems.append(
            "self-test capacity: validator accepted a snapshot missing "
            "its headroom"
        )
    fleet = aggregate_fleet([snap, json_roundtrip(snap), None])
    if not fleet or fleet.get("replicas_reporting") != 2:
        problems.append(
            "self-test capacity: fleet fold miscounted live replicas"
        )

    # The serve snapshot carries the block; the validator must police it
    # there too.
    carried = stats.snapshot()
    carried["capacity"] = json_roundtrip(snap)
    problems += validate_serve_snapshot(
        carried, "self-test capacity-bearing serve snapshot"
    )
    carried["capacity"]["rejection_rate"] = -0.5
    if not validate_serve_snapshot(carried):
        problems.append(
            "self-test capacity: serve-snapshot validator accepted a "
            "negative rejection rate in the carried block"
        )

    block = {
        "predicted_saturation_rps": 2.4,
        "measured_saturation_rps": 2.2,
        "prediction_error_pct": 9.1,
        "alerts_hot": 1, "alerts_cold": 0,
        "recompiles_steady_state": 0,
        "overhead_pct": 0.3,
        "capacity_tokens_per_s": 38.4,
        "service_rate_per_slot": 4.8,
        "hot_rps": 3.3, "cold_rps": 1.1,
        "hot_utilization": 0.97, "ts_points": 240,
    }
    problems += validate_bench_slo(block, "self-test bench slo")
    if not validate_bench_slo(
        {k: v for k, v in block.items() if k != "alerts_cold"}
    ):
        problems.append(
            "self-test bench slo: validator accepted a block missing "
            "the cold-arm alert pin"
        )
    if not validate_bench_slo(
        {**block, "measured_saturation_rps": 0.0}
    ):
        problems.append(
            "self-test bench slo: validator accepted a zero measured "
            "saturation knee"
        )
    return problems


def json_roundtrip(doc):
    return json.loads(json.dumps(doc))


def _self_test_spec_decode(stats) -> list:
    """Speculative-decoding producers vs their schema: a snapshot with
    the engine's real spec counter/gauge names, the per-request wire
    fields, and the bench spec_decode block — plus negatives (an
    acceptance rate outside [0, 1] and accepted > drafted must FAIL)."""
    stats.bump("spec_drafted", 12)
    stats.bump("spec_accepted", 9)
    stats.bump("spec_emitted", 12)
    stats.bump("spec_ticks", 3)
    stats.set_gauges(spec_acceptance_rate=0.75,
                     spec_goodput_tokens_per_sec=40.0)
    problems = validate_serve_snapshot(
        stats.snapshot(), "self-test spec snapshot"
    )
    problems += validate_serve_request(
        {
            "type": "serve_request", "rid": "abc", "prompt": [1, 2],
            "max_new_tokens": 4, "temperature": 0.7, "top_k": 8,
            "spec": 4, "eos_token_id": None, "deadline_s": None,
            "reply": ["127.0.0.1", 12345],
        },
        "self-test spec request",
    )
    problems += validate_bench_spec_decode(
        {
            "spec_k": 4, "draft_layers": 2, "target_layers": 8,
            "tokens_per_sec": 900.0, "baseline_tokens_per_sec": 400.0,
            "vs_baseline": 2.25, "acceptance_rate": 0.92,
            "recompiles_steady_state": 0,
            "baseline_recompiles_steady_state": 0,
            "drafted": 480, "accepted": 441, "emitted": 560,
            "greedy_parity": True, "requests": 32, "max_new_tokens": 16,
            "acceptance_sweep": [{
                "noise": 0.02, "acceptance_rate": 0.71,
                "tokens_per_sec": 700.0, "vs_baseline": 1.75,
            }],
        },
        "self-test bench spec_decode",
    )
    if not validate_bench_spec_decode({"spec_k": 4}):
        problems.append(
            "self-test spec_decode: validator accepted a block missing "
            "the A/B arms"
        )
    if not validate_bench_spec_decode(
        {
            "spec_k": 4, "tokens_per_sec": 1.0,
            "baseline_tokens_per_sec": 1.0, "vs_baseline": 1.0,
            "acceptance_rate": 1.5, "recompiles_steady_state": 0,
            "baseline_recompiles_steady_state": 0,
        }
    ):
        problems.append(
            "self-test spec_decode: validator accepted acceptance > 1"
        )
    broken_sweep = validate_bench_spec_decode(
        {
            "spec_k": 4, "tokens_per_sec": 1.0,
            "baseline_tokens_per_sec": 1.0, "vs_baseline": 1.0,
            "acceptance_rate": 0.9, "recompiles_steady_state": 0,
            "baseline_recompiles_steady_state": 0,
            "acceptance_sweep": [
                {"noise": 0.01},  # arm 0 broken (missing fields)
                {"noise": 0.02, "acceptance_rate": 1.5,
                 "tokens_per_sec": 1.0, "vs_baseline": 1.0},
            ],
        }
    )
    if not any("acceptance_sweep[1]" in p for p in broken_sweep):
        problems.append(
            "self-test spec_decode: arm-0 failure suppressed arm-1's "
            "range check"
        )
    bad = stats.snapshot()
    bad["counters"]["spec_accepted"] = (
        bad["counters"]["spec_drafted"] + 1
    )
    if not validate_serve_snapshot(bad):
        problems.append(
            "self-test spec snapshot: validator accepted "
            "accepted > drafted"
        )
    return problems


def _self_test_host_overhead() -> list:
    """The megastep bench block: the shape bench.py emits must pass, and
    a drifted producer (unknown key, bad megastep_k) must NOT."""
    problems = validate_bench_host_overhead(
        {
            "fit_vs_raw": 0.97,
            "dispatches_per_opt_step": 1.0,
            "megastep_k": 8,
            "megastep_dispatches_per_opt_step": 0.125,
            "megastep_tokens_per_sec": 1234.5,
            "megastep_speedup": 1.02,
        },
        "self-test host_overhead",
    )
    # All-null probes (every arm best-effort) are a legal block too.
    problems += validate_bench_host_overhead(
        {"fit_vs_raw": None, "megastep_speedup": None},
        "self-test host_overhead nulls",
    )
    if not validate_bench_host_overhead({"unknown_key": 1}):
        problems.append(
            "self-test host_overhead: validator accepted an unknown key"
        )
    if not validate_bench_host_overhead({"megastep_k": 0}):
        problems.append(
            "self-test host_overhead: validator accepted megastep_k=0"
        )
    return problems


def scan_fixture_bundle() -> list:
    """The committed fixture keeps the validator honest against a
    full-featured bundle (spans, logs, counters) without needing a
    crash to reproduce one."""
    if not os.path.exists(FIXTURE_BUNDLE):
        return [f"missing fixture {os.path.relpath(FIXTURE_BUNDLE, REPO_ROOT)}"]
    try:
        with open(FIXTURE_BUNDLE) as f:
            doc = json.load(f)
    except ValueError as e:
        return [f"flight_bundle.json: not JSON ({e})"]
    return validate_flight_bundle(doc, "fixture flight_bundle.json")


def scan_bench_files() -> list:
    problems = []
    for path in sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as e:
            problems.append(f"{name}: not JSON ({e})")
            continue
        block = doc.get("telemetry")
        if block is not None:
            problems += validate_bench_telemetry(block, f"{name}:telemetry")
        fault = doc.get("fault")
        if fault is not None:  # pre-recovery-plane rounds lack it
            problems += validate_bench_fault(fault, f"{name}:fault")
        host = doc.get("host_overhead")
        if host is not None:  # pre-megastep rounds lack it
            problems += validate_bench_host_overhead(
                host, f"{name}:host_overhead"
            )
        serve = doc.get("serve")
        if serve is not None:  # pre-serving rounds lack it
            problems += validate_bench_serve(serve, f"{name}:serve")
        spec = doc.get("spec_decode") or (serve or {}).get("spec_decode")
        if spec is not None:  # pre-speculation rounds lack it
            problems += validate_bench_spec_decode(
                spec, f"{name}:spec_decode"
            )
        disagg = (doc.get("serve_disagg")
                  or (serve or {}).get("serve_disagg"))
        if disagg is not None:  # pre-disaggregation rounds lack it
            problems += validate_bench_serve_disagg(
                disagg, f"{name}:serve_disagg"
            )
        chaos = (doc.get("serve_chaos")
                 or (serve or {}).get("serve_chaos"))
        if chaos is not None:  # pre-serve-chaos rounds lack it
            problems += validate_bench_serve_chaos(
                chaos, f"{name}:serve_chaos"
            )
        prefix = (doc.get("prefix_cache")
                  or (serve or {}).get("prefix_cache"))
        if prefix is not None:  # pre-prefix-cache rounds lack it
            problems += validate_bench_prefix_cache(
                prefix, f"{name}:prefix_cache"
            )
        chunked = (doc.get("chunked_prefill")
                   or (serve or {}).get("chunked_prefill"))
        if chunked is not None:  # pre-chunked-prefill rounds lack it
            problems += validate_bench_chunked_prefill(
                chunked, f"{name}:chunked_prefill"
            )
        trace = doc.get("trace") or (serve or {}).get("trace")
        if trace is not None:  # pre-tracing rounds lack it
            problems += validate_bench_trace(trace, f"{name}:trace")
        slo = doc.get("slo") or (serve or {}).get("slo")
        if slo is not None:  # pre-SLO-plane rounds lack it
            problems += validate_bench_slo(slo, f"{name}:slo")
        multi_lora = (doc.get("multi_lora")
                      or (serve or {}).get("multi_lora"))
        if multi_lora is not None:  # pre-multi-tenant rounds lack it
            problems += validate_bench_multi_lora(
                multi_lora, f"{name}:multi_lora"
            )
        mpmd = doc.get("mpmd")
        if mpmd is not None:  # pre-MPMD rounds lack it
            problems += validate_bench_mpmd(mpmd, f"{name}:mpmd")
        overlap = doc.get("comm_overlap")
        if overlap is not None:  # pre-overlap rounds lack it
            problems += validate_bench_comm_overlap(
                overlap, f"{name}:comm_overlap"
            )
        opt_state = doc.get("opt_state")
        if opt_state is not None:  # pre-HBM-diet rounds lack it
            problems += validate_bench_opt_state(
                opt_state, f"{name}:opt_state"
            )
        residual = doc.get("residual_policy")
        if residual is not None:  # pre-HBM-diet rounds lack it
            problems += validate_bench_residual_policy(
                residual, f"{name}:residual_policy"
            )
        programs = doc.get("programs")
        if programs is not None:  # pre-ledger rounds lack it
            problems += validate_bench_programs(
                programs, f"{name}:programs"
            )
    return problems


def scan_paths(paths) -> list:
    problems = []
    for path in paths:
        name = os.path.basename(path)
        try:
            if path.endswith(".jsonl"):
                # Span dumps and heartbeat streams are both JSONL;
                # route on content.
                with open(path) as f:
                    lines = f.readlines()
                first = json.loads(lines[0]) if lines else {}
                if isinstance(first, dict) and "type" in first:
                    for i, line in enumerate(lines):
                        line = line.strip()
                        if line:
                            problems += validate_stream_item(
                                json.loads(line), f"{name}:{i + 1}"
                            )
                else:
                    problems += validate_span_jsonl(lines, name)
            else:
                with open(path) as f:
                    doc = json.load(f)
                if isinstance(doc, dict) and "schema" in doc:
                    problems += validate_flight_bundle(doc, name)
                else:
                    problems += validate_chrome_trace(doc, name)
        except (OSError, ValueError) as e:
            problems.append(f"{name}: unreadable ({e})")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Validate telemetry artifact schemas "
        "(span/heartbeat JSONL, Chrome traces, flight bundles, "
        "BENCH_*.json telemetry blocks)."
    )
    ap.add_argument("paths", nargs="*",
                    help="extra span/heartbeat .jsonl, chrome .json or "
                    "flight-bundle .json files to check")
    args = ap.parse_args(argv)

    problems = (self_test() + scan_bench_files() + scan_fixture_bundle()
                + scan_paths(args.paths))
    if problems:
        for p in problems:
            print(f"check_telemetry_schema: {p}", file=sys.stderr)
        print(f"check_telemetry_schema: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    print("check_telemetry_schema: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
