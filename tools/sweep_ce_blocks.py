"""Sweep fused-CE kernel block sizes on the current backend.

Usage: ``python tools/sweep_ce_blocks.py [--steps 8]``

Times fwd+bwd of the fused LM-head CE at GPT-2-small shapes
(B=16, T=1023, d=768, V=50304) for a grid of (block_t, block_v)
pairs, patching the module constants per trial.  Larger blocks cut the
operand re-streaming (the t-major kernels re-read the full wte per
token block; the v-major dw kernel re-reads x per vocab block) at the
cost of VMEM; compile failures are reported and skipped, not fatal.

Prints one line per config plus the winner; run on real TPU hardware —
on CPU (interpreter) the timings are meaningless and the script exits.
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--bt", type=int, nargs="*", default=[256, 512, 1024])
    ap.add_argument("--bv", type=int, nargs="*", default=[256, 512, 1024])
    args = ap.parse_args()

    from bench import _detect_backend

    if _detect_backend() != "tpu":
        print("not on TPU — interpreter timings are meaningless; exiting")
        return

    from ray_lightning_tpu.ops import cross_entropy as ce

    default_bt, default_bv = ce._CE_BLOCK_T, ce._CE_BLOCK_V
    B, T, d, V = 16, 1023, 768, 50304
    kx, kw, kt = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (B, T, d), jnp.bfloat16)
    wte = (jax.random.normal(kw, (V, d), jnp.float32) * 0.02)
    t = jax.random.randint(kt, (B, T), 0, V)

    def loss(x, w):
        return ce.fused_lm_head_cross_entropy(
            x, w, t, use_pallas=True).mean()

    results = []
    for bt, bv in itertools.product(args.bt, args.bv):
        ce._CE_BLOCK_T, ce._CE_BLOCK_V = bt, bv
        from ray_lightning_tpu.ops import kernel_probe

        kernel_probe._CACHE.clear()
        try:
            g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
            out = g(x, wte)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = g(x, wte)
            jax.block_until_ready(out)
            ms = (time.perf_counter() - t0) / args.steps * 1e3
            results.append((ms, bt, bv))
            print(f"bt={bt:5d} bv={bv:5d}  {ms:7.2f} ms/step")
        except Exception as e:
            print(f"bt={bt:5d} bv={bv:5d}  FAILED "
                  f"{type(e).__name__}: {str(e)[:90]}")
    if results:
        ms, bt, bv = min(results)
        print(f"best: bt={bt} bv={bv} at {ms:.2f} ms/step "
              f"(current defaults: {default_bt}/{default_bv})")


if __name__ == "__main__":
    main()
