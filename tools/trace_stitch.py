"""trace_stitch — merge a fleet's span exports into one Perfetto trace.

Point it at a telemetry directory (or at a ``router-live.json`` — its
parent directory is used, so tab-completing the live artifact an
operator is already watching Just Works).  It merges every
``trace-*.jsonl`` component export (serve engines, router, prefill
workers, MPMD stage runners) into ONE Chrome ``trace_event`` document
with cross-process flow arrows, and prints the critical-path report:
stitch coverage, per-phase p50/p95, and the slowest-K requests'
``queue_wait → … → first_token`` decomposition (plus the per-step
compute-vs-blocked MPMD timeline when stage traces are present).

Usage:
    python tools/trace_stitch.py rlt_logs/serve/telemetry
    python tools/trace_stitch.py rlt_logs/serve/telemetry/router-live.json
    python tools/trace_stitch.py <dir> --out merged-trace.json --slowest 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_lightning_tpu.telemetry import trace_collect  # noqa: E402


def resolve_dir(path: str) -> str:
    """A telemetry dir, or any file inside one (router-live.json /
    serve-live.json discovery)."""
    if os.path.isdir(path):
        return path
    if os.path.isfile(path):
        return os.path.dirname(os.path.abspath(path)) or "."
    raise FileNotFoundError(f"no such file or directory: {path}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Stitch per-process span exports into one "
        "Perfetto trace + a critical-path report."
    )
    ap.add_argument(
        "path",
        help="telemetry dir holding trace-*.jsonl exports (or a "
        "router-live.json/serve-live.json inside one)",
    )
    ap.add_argument(
        "--out", default=None,
        help="merged Chrome-trace output path (default: "
        "<dir>/trace-merged.json)",
    )
    ap.add_argument("--slowest", type=int, default=5, metavar="K",
                    help="requests in the critical-path report")
    ap.add_argument("--no-report", action="store_true",
                    help="write the merged trace only")
    args = ap.parse_args(argv)

    try:
        trace_dir = resolve_dir(args.path)
    except FileNotFoundError as e:
        print(f"trace_stitch: {e}", file=sys.stderr)
        return 2
    spans = trace_collect.load_trace_dir(trace_dir)
    if not spans:
        print(
            f"trace_stitch: no trace-*.jsonl under {trace_dir} "
            "(tracing off? fleet not torn down yet? exports land at "
            "member close)",
            file=sys.stderr,
        )
        return 1
    out = args.out or os.path.join(trace_dir, "trace-merged.json")
    doc = trace_collect.stitch_chrome(spans)
    with open(out, "w") as f:
        json.dump(doc, f)
    n_x = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_flow = sum(1 for e in doc["traceEvents"] if e.get("ph") == "s")
    print(f"trace_stitch: {len(spans)} span(s) from "
          f"{len(doc['otherData']['sources'])} component(s) -> {out} "
          f"({n_x} slices, {n_flow} cross-process arrows) — open in "
          f"https://ui.perfetto.dev")
    if not args.no_report:
        print(trace_collect.format_report(spans, slowest_k=args.slowest))
    return 0


if __name__ == "__main__":
    sys.exit(main())
