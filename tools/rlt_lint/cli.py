"""rlt-lint CLI: file scoping, baseline semantics, fixture self-test.

Usage (mirrors ``format.sh``'s scoping)::

    python -m tools.rlt_lint             # changed files vs origin/main
    python -m tools.rlt_lint --all       # the whole scanned tree
    python -m tools.rlt_lint --baseline tools/rlt_lint/baseline.json
    python -m tools.rlt_lint --selftest  # fixture matrix (format.sh)
    python -m tools.rlt_lint path.py ... # explicit paths

Baseline semantics: entries are keyed ``(path, rule, stripped source
text)`` with a ``count`` — line numbers drift, the flagged text does
not.  A finding matching an entry is suppressed (up to ``count``
times); an entry whose file was scanned but matched fewer findings
than its count (including none) is stale and reported as RLT000 so
the baseline only ever shrinks — leftover count budget must never
suppress a future same-text finding without review.  The
committed baseline must stay enumerated in docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from tools.rlt_lint.core import (
    Config, Finding, check_source, load_env_registry, load_schema_keys,
    repo_config,
)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(
    "tools", "rlt_lint", "baseline.json"
)

#: Scanned universe: the package, tooling, bench drivers and examples.
#: Tests are exempt (they deliberately poke invariants), and the
#: fixture corpus is lint-bait by construction.
_SCAN_PREFIXES = ("ray_lightning_tpu/", "tools/", "examples/")
_SCAN_ROOT_FILES = re.compile(r"^(bench[\w]*|__graft_entry__)\.py$")
_EXCLUDE_PREFIXES = ("tools/rlt_lint/fixtures/",)


def in_scope(relpath: str) -> bool:
    relpath = relpath.replace(os.sep, "/")
    if any(relpath.startswith(p) for p in _EXCLUDE_PREFIXES):
        return False
    if any(relpath.startswith(p) for p in _SCAN_PREFIXES):
        return relpath.endswith(".py")
    return bool(_SCAN_ROOT_FILES.match(relpath))


def _git_files(all_files: bool, cwd: Optional[str] = None) -> List[str]:
    cwd = cwd or _REPO_ROOT

    def git_lines(*cmd):
        out = subprocess.run(
            ["git", *cmd], capture_output=True, text=True, cwd=cwd
        ).stdout
        return [line for line in out.splitlines() if line.strip()]

    # Untracked files are invisible to both ls-files (default) and
    # diff — without this a brand-new in-scope file ships unlinted and
    # breaks the NEXT committer's run once tracked.
    untracked = git_lines(
        "ls-files", "--others", "--exclude-standard", "*.py"
    )
    if all_files:
        files = git_lines("ls-files", "*.py") + untracked
    else:
        try:
            base = subprocess.run(
                ["git", "merge-base", "HEAD", "origin/main"],
                capture_output=True, text=True, cwd=cwd,
            ).stdout.strip() or "HEAD"
        except OSError:
            base = "HEAD"
        # ACMR: a renamed-and-edited file is still changed (git shows
        # status R under default rename detection; plain ACM drops it).
        files = git_lines(
            "diff", "--name-only", "--diff-filter=ACMR", base,
            "--", "*.py"
        ) + untracked
    seen, out = set(), []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> List[Dict]:
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    for e in entries:
        for key in ("path", "rule", "text"):
            if key not in e:
                raise ValueError(f"baseline entry missing {key!r}: {e}")
        e.setdefault("count", 1)
    return entries


def apply_baseline(
    findings: List[Finding], entries: List[Dict], scanned: List[str]
) -> Tuple[List[Finding], List[str]]:
    """Returns (unsuppressed findings, stale-entry messages)."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in entries:
        key = (e["path"], e["rule"], e["text"])
        budget[key] = budget.get(key, 0) + int(e["count"])
    used: Dict[Tuple[str, str, str], int] = {}
    kept: List[Finding] = []
    for f in findings:
        key = (f.path, f.rule, f.text)
        if used.get(key, 0) < budget.get(key, 0):
            used[key] = used.get(key, 0) + 1
        else:
            kept.append(f)
    stale: List[str] = []
    scanned_set = set(scanned)
    for key, n in budget.items():
        path, rule, text = key
        if path not in scanned_set:
            continue
        u = used.get(key, 0)
        if u == 0:
            stale.append(
                f"{path}: RLT000 stale baseline entry ({rule}: {text!r}) "
                f"— the finding is gone; prune it from the baseline"
            )
        elif u < n:
            # A partially-consumed count is stale too: the leftover
            # budget would silently suppress a FUTURE same-text finding
            # without noqa or review, breaking the only-ever-shrinks
            # invariant.
            stale.append(
                f"{path}: RLT000 stale baseline entry ({rule}: {text!r}) "
                f"— count is {n} but only {u} matched; shrink the count"
            )
    return kept, stale


# ---------------------------------------------------------------------------
# Fixture self-test
# ---------------------------------------------------------------------------

_FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures")
_DIRECTIVE_RE = re.compile(r"#\s*rlt-fixture:\s*(\S+)\s*(.*)$")
_EXPECT_RE = re.compile(r"#\s*expect\[([A-Z0-9]+)\]")


def _fixture_config(src: str, relname: str) -> Config:
    """Build a per-fixture Config from ``# rlt-fixture:`` directives."""
    hot_jit: Dict[str, frozenset] = {}
    hot_sync: Dict[str, frozenset] = {}
    wall, perf, envl = set(), set(), set()
    ledger_paths: List[str] = []
    producers: Dict[str, Dict[str, str]] = {}
    schema_keys: Dict[str, Tuple[frozenset, frozenset]] = {}
    env_registry = {"RLT_KNOWN"}
    for line in src.splitlines():
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        rest = []
        for tok in m.group(2).split():
            if tok.startswith("#"):
                break  # trailing comment (e.g. an expect marker)
            rest.append(tok)
        if kind == "hot-jit":
            hot_jit[relname] = frozenset(rest)
        elif kind == "hot-sync":
            hot_sync[relname] = frozenset(rest)
        elif kind == "wall-clock-tracer":
            wall.add(relname)
        elif kind == "perf-timing":
            perf.add(relname)
        elif kind == "trace-envelope":
            envl.add(relname)
        elif kind == "producer":
            producers.setdefault(relname, {})[rest[0]] = rest[1]
        elif kind == "schema-keys":
            prefix = rest[0]
            req: frozenset = frozenset()
            opt: frozenset = frozenset()
            for tok in rest[1:]:
                side, _, csv = tok.partition("=")
                vals = frozenset(v for v in csv.split(",") if v)
                if side == "required":
                    req = vals
                elif side == "optional":
                    opt = vals
            schema_keys[prefix] = (req, opt)
        elif kind == "env-registry":
            env_registry.update(rest)
        elif kind == "ledger-scope":
            ledger_paths.append(relname)
        else:
            raise ValueError(f"unknown fixture directive {kind!r}")
    return Config(
        hot_jit=hot_jit, hot_sync=hot_sync,
        wall_clock_tracer_files=frozenset(wall),
        perf_timing_files=frozenset(perf),
        trace_envelope_files=frozenset(envl),
        schema_producers=producers, schema_keys=schema_keys,
        env_registry=frozenset(env_registry),
        ledger_paths=tuple(ledger_paths),
    )


def run_fixture(path: str) -> Tuple[List[str], int]:
    """Check one fixture file: every ``# expect[RLTxxx]`` line must be
    flagged with exactly that rule, and nothing else may fire.
    Returns (mismatch messages, expectation count)."""
    with open(path) as f:
        src = f.read()
    relname = os.path.basename(path)
    config = _fixture_config(src, relname)
    expected = set()
    for i, line in enumerate(src.splitlines(), 1):
        for m in _EXPECT_RE.finditer(line):
            expected.add((i, m.group(1)))
    got = {
        (f.line, f.rule)
        for f in check_source(relname, src, config)
    }
    problems = []
    for line, rule in sorted(expected - got):
        problems.append(
            f"{relname}:{line}: expected {rule} but the rule did not fire"
        )
    for line, rule in sorted(got - expected):
        problems.append(
            f"{relname}:{line}: unexpected {rule} finding"
        )
    return problems, len(expected)


def selftest() -> int:
    """Drive the committed fixture corpus.  Each rule ships flagged AND
    clean snippets; a rule change that breaks either fails format.sh."""
    names = sorted(
        n for n in os.listdir(_FIXTURE_DIR) if n.endswith(".py")
    )
    if not names:
        print("rlt_lint selftest: no fixtures found", file=sys.stderr)
        return 1
    rules_seen = set()
    total = 0
    failed = False
    for name in names:
        problems, n_expected = run_fixture(
            os.path.join(_FIXTURE_DIR, name)
        )
        total += n_expected
        m = re.match(r"(rlt\d{3})", name)
        if m:
            rules_seen.add(m.group(1).upper())
        for p in problems:
            print(f"rlt_lint selftest: {p}", file=sys.stderr)
            failed = True
    missing = {f"RLT{i:03d}" for i in range(9)} - rules_seen
    if missing:
        print(
            f"rlt_lint selftest: no fixture exercises "
            f"{', '.join(sorted(missing))}", file=sys.stderr,
        )
        failed = True
    if failed:
        return 1
    print(
        f"rlt_lint selftest OK: {len(names)} fixtures, "
        f"{total} expectations, rules "
        f"{', '.join(sorted(rules_seen))}"
    )
    return 0


# ---------------------------------------------------------------------------
# Main
# ---------------------------------------------------------------------------

def run_lint(paths: List[str], baseline_path: Optional[str],
             config: Optional[Config] = None) -> int:
    config = config or repo_config(_REPO_ROOT)
    findings: List[Finding] = []
    scanned: List[str] = []
    for rel in sorted(paths):
        # Normalize to the repo-relative forward-slash form every
        # path-keyed registry (hot paths, tracers, producers, the
        # baseline) is keyed on — an absolute or ./-prefixed path
        # would otherwise silently match NO rules and report a false
        # clean.
        rel = os.path.relpath(os.path.abspath(
            rel if os.path.isabs(rel)
            else os.path.join(_REPO_ROOT, rel)
        ), _REPO_ROOT)
        rel = rel.replace(os.sep, "/")
        abspath = os.path.join(_REPO_ROOT, rel)
        try:
            with open(abspath) as f:
                src = f.read()
        except OSError as e:
            print(f"rlt_lint: cannot read {rel}: {e}", file=sys.stderr)
            return 2
        scanned.append(rel)
        findings.extend(check_source(rel, src, config))
    stale: List[str] = []
    if baseline_path:
        try:
            entries = load_baseline(os.path.join(_REPO_ROOT, baseline_path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"rlt_lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        findings, stale = apply_baseline(findings, entries, scanned)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    for f in findings:
        print(f.render())
    for msg in stale:
        print(msg)
    n = len(findings) + len(stale)
    if n:
        print(
            f"rlt_lint: {n} finding(s) in {len(scanned)} file(s) — fix, "
            f"'# rlt: noqa[RLT00x] reason', or baseline "
            f"(docs/STATIC_ANALYSIS.md)"
        )
        return 1
    print(f"rlt_lint: OK ({len(scanned)} file(s))")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="rlt_lint",
        description="AST invariant checker (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("--all", action="store_true",
                    help="scan the whole tree (default: changed files)")
    ap.add_argument("--changed", action="store_true",
                    help="scan files changed vs origin/main (default)")
    ap.add_argument("--baseline", default=None,
                    help="findings baseline JSON (grandfathered sites)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the committed baseline")
    ap.add_argument("--selftest", action="store_true",
                    help="run the per-rule fixture matrix and exit")
    ap.add_argument("paths", nargs="*",
                    help="explicit repo-relative files (overrides scope)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.paths:
        paths = [p for p in args.paths]
    else:
        paths = [p for p in _git_files(args.all) if in_scope(p)]
    if not paths:
        print("rlt_lint: no python files in scope")
        return 0
    baseline = args.baseline
    if baseline is None and not args.no_baseline:
        if os.path.exists(os.path.join(_REPO_ROOT, DEFAULT_BASELINE)):
            baseline = DEFAULT_BASELINE
    return run_lint(paths, baseline)


if __name__ == "__main__":
    sys.exit(main())
