# rlt-fixture: trace-envelope
"""RLT004 fixture: cross-process envelopes must use the wall clock."""
import time


def inject(item, ctx):
    item["trace"] = {
        "trace_id": ctx,
        "ts": time.time(),   # clean: wall clock IS the envelope epoch
    }
    return item


def bad_envelope(item):
    item["sent"] = time.perf_counter()    # expect[RLT004]
    t0 = time.perf_counter()              # expect[RLT004]
    return item, t0


def wall_ok():
    # Clean: time.time is unrestricted in envelope modules.
    return time.time()
