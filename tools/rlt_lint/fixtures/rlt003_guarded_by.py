"""RLT003 fixture: guarded-attribute lock discipline."""
import threading


class Feed:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = []        # guarded by self._lock
        self._failed = []      # guarded by self._lock
        self.free = 0          # clean: unguarded attribute

    def add(self, item):
        with self._lock:
            self._done.append(item)   # clean: inside the lock

    def add_failed(self, item):
        self._failed.append(item)     # expect[RLT003]

    def drain(self):
        items = self._done            # expect[RLT003]
        self.free += 1                # clean: not a guarded attr
        return items

    def _drain_locked(self):  # rlt: holds self._lock
        # Clean: the method asserts its caller holds the lock.
        items, self._done = self._done, []
        return items

    def deferred(self):
        with self._lock:
            # A closure defined under the lock does NOT run under it.
            def cb():
                return len(self._done)   # expect[RLT003]

            return cb

    def peek_suppressed(self):
        return list(self._done)  # rlt: noqa[RLT003] stale-ok snapshot

    def sneaky(self):
        # A guard comment pasted on a USE site is not a suppression —
        # only the declaration assignment is exempt.
        return len(self._done)  # guarded by self._lock  # expect[RLT003]


class Other:
    def __init__(self):
        self._done = []   # clean: same name, class never annotates it

    def touch(self):
        return self._done
