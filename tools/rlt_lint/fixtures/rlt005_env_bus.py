# rlt-fixture: env-registry RLT_KNOWN RLT_ALSO_KNOWN
"""RLT005 fixture: RLT_* env reads vs the env_bus registry."""
import os


def read_knobs():
    a = os.environ.get("RLT_KNOWN")              # clean: registered
    b = os.getenv("RLT_ALSO_KNOWN", "x")         # clean: registered
    c = os.environ.get("RLT_MYSTERY_KNOB")       # expect[RLT005]
    d = os.environ["RLT_OTHER_MYSTERY"]          # expect[RLT005]
    e = os.environ.get("JAX_PLATFORMS")          # clean: not RLT_*
    return a, b, c, d, e


def dynamic(name):
    # Clean: non-literal reads cannot be checked statically (the
    # monitor's from_env map); the registry still documents them.
    return os.environ.get(name)
