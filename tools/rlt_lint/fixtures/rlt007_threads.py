"""RLT007 fixture: thread hygiene."""
import threading


def beat_loop():
    while True:
        try:
            publish()
        except Exception:                 # expect[RLT007]
            pass


def pump_loop():
    while True:
        try:
            pump()
        except:                           # expect[RLT007]
            return


def drive_loop():
    # Clean: typed, handled — not swallowed.
    try:
        pump()
    except (OSError, ConnectionError):
        return


def publish():
    # Clean: not a thread target — narrow swallows elsewhere are
    # flake8/review territory, not RLT007's.
    try:
        pass
    except Exception:
        pass


def pump():
    pass


def start():
    t1 = threading.Thread(target=beat_loop)   # expect[RLT007]
    t2 = threading.Thread(target=pump_loop, daemon=True)   # clean
    t3 = threading.Thread(target=drive_loop, daemon=False)  # clean
    return t1, t2, t3
