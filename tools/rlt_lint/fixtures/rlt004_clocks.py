# rlt-fixture: perf-timing
# rlt-fixture: wall-clock-tracer
"""RLT004 fixture: wall vs perf_counter vs jit-purity discipline."""
import time

import jax

from telemetry.spans import SpanTracer  # fixture-local import shape


def measure_step():
    t0 = time.time()                      # expect[RLT004]
    dur = time.time() - t0                # expect[RLT004]
    good0 = time.perf_counter()           # clean: perf timing module
    return dur, time.perf_counter() - good0


def envelope(rank):
    return {
        "type": "heartbeat",
        "rank": rank,
        "ts": time.time(),   # clean: wall-timestamp dict key
    }


def make_tracers(enabled):
    # Clean: distributed tracer passes the shared wall epoch.
    a = SpanTracer(enabled=enabled, clock=time.time)
    b = SpanTracer(enabled=enabled)       # expect[RLT004]
    return a, b


def _raw_step(state, batch):
    noise = time.perf_counter()           # expect[RLT004]
    seed = __import__("random").random
    return state, noise, seed


_STEP = jax.jit(_raw_step)


@jax.jit
def _other_step(x):
    t = time.time()                       # expect[RLT004]
    return x, t


def host_helper():
    # Clean: not jit-wrapped — perf_counter is the right clock here.
    return time.perf_counter()
