# rlt-fixture: hot-jit Engine.step tick_helper
"""RLT001 fixture: jit construction on registered hot paths."""
import functools

import jax


# Clean: module-level jit construction is the intended shape.
_DECODE = jax.jit(lambda x: x + 1)


# Clean: module-level @partial(jax.jit) — one object for the process.
@functools.partial(jax.jit, static_argnums=0)
def _scale(n, x):
    return x * n


@functools.lru_cache(maxsize=8)
def make_fn(mesh):
    # Clean: lru_cache'd factory — one construction per mesh.
    return jax.jit(lambda t: t, out_shardings=mesh)


def tick_helper(x):
    fn = jax.jit(lambda t: t * 2)  # expect[RLT001]
    return fn(x)


class Engine:
    def __init__(self):
        # Clean: not a registered hot path — engine build time.
        self._fn = jax.jit(lambda t: t)

    def step(self, x):
        y = self._fn(x)          # clean: using the cached jit object
        g = jax.jit(self._fn)    # expect[RLT001]

        @jax.jit               # expect[RLT001]
        def inner(t):
            return t - 1

        # @partial(jax.jit, ...) constructs a fresh jit object just
        # like @jax.jit — the required form for static/donated args.
        @functools.partial(jax.jit, donate_argnums=0)  # expect[RLT001]
        def donated(t):
            return t * 3

        return inner(donated(g(y)))

    def build(self, x):
        # Clean: not registered — setup-time construction is fine.
        return jax.jit(lambda t: t)(x)
