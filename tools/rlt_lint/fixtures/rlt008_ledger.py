# rlt-fixture: ledger-scope
"""RLT008 fixture: import-time jit construction in ledger-scoped files
must route through telemetry.program_ledger.ledgered_jit."""
from functools import partial

import jax
from jax.experimental.pjit import pjit

from ray_lightning_tpu.telemetry.program_ledger import ledgered_jit


def _step(x):
    return x + 1


step = jax.jit(_step)  # expect[RLT008]

sharded = pjit(_step)  # expect[RLT008]

donated = partial(jax.jit, donate_argnums=0)(_step)  # expect[RLT008]


@jax.jit  # expect[RLT008]
def decorated_step(x):
    return x * 2


@partial(jax.jit, static_argnums=0)  # expect[RLT008]
def static_step(n, x):
    return x * n


class Holder:
    # class attributes are still built at import time — same bypass
    step = jax.jit(_step)  # expect[RLT008]


# clean: routed through the ledger registration wrapper
ledgered = ledgered_jit(_step, site="fixture/step")

# clean: a partial alone is a factory, not a compiled program
jit_donating = partial(jax.jit, donate_argnums=0)


# clean: jit built inside a function body is RLT001's domain, not RLT008's
def build_step():
    return jax.jit(_step)


# clean: reasoned escape hatch for deliberate out-of-ledger programs
reference = jax.jit(_step)  # rlt: noqa[RLT008] reference impl, never dispatched in prod
