# rlt-fixture: hot-sync Engine.step loop_body
"""RLT002 fixture: host/device syncs inside hot-loop bodies."""
import jax
import numpy as np


def loop_body(batch, metric):
    lr = float(metric)                    # expect[RLT002]
    host = np.asarray(batch)              # expect[RLT002]
    jax.block_until_ready(batch)          # expect[RLT002]
    n = int(batch.shape)                  # expect[RLT002]
    v = metric.item()                     # expect[RLT002]
    k = int(7)      # clean: constant args never touch the device
    return lr, host, n, v, k


def setup(batch):
    # Clean: not a registered hot-loop body.
    return float(batch.mean()), np.asarray(batch)


class Engine:
    def step(self, x):
        first = int(x)  # rlt: noqa[RLT002] deliberate TTFT sync
        ok = jax.device_get(x)            # expect[RLT002]
        return first, ok

    def report(self, x):
        # Clean: reporting path, not registered.
        return x.item()
