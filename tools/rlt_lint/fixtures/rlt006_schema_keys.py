# rlt-fixture: producer make_beat BEAT
# rlt-fixture: producer span_dict SPAN!any
# rlt-fixture: schema-keys BEAT required=type,rank,ts optional=done,load
# rlt-fixture: schema-keys SPAN required=name,ts,dur optional=args
"""RLT006 fixture: producer dict keys vs validator key sets."""
import time


def make_beat(rank, done):
    beat = {
        "type": "beat",                   # clean: anchored + known
        "rank": rank,
        "ts": time.time(),
        "typo_rank": rank,                # expect[RLT006]
    }
    if done:
        beat["done"] = True               # clean: optional key
        beat["dnoe"] = True               # expect[RLT006]
    helper = {"scratch": 1}   # clean: no "type" anchor, not checked
    return beat, helper


def span_dict(span):
    d = {
        "name": span,                     # clean: !any producer
        "ts": 0.0,
        "dur": 1.0,
        "detph": 0,                       # expect[RLT006]
    }
    d["args"] = {}                        # clean: optional key
    return d


def unrelated(rank):
    # Clean: not a registered producer — keys are free-form.
    return {"type": "whatever", "made_up": rank}
