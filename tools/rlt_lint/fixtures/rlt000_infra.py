# rlt-fixture: hot-sync Engine.gone_method  # expect[RLT000]
"""RLT000 fixture: suppression and registry hygiene.

The ``hot-sync`` directive on line 1 registers ``Engine.gone_method``,
which does not exist below — registry drift is itself a finding,
reported at line 1 so the config moves with the refactor.
"""


def suppressions(x):
    a = float(x)  # rlt: noqa[RLT999] unknown rule  # expect[RLT000]
    b = float(x)  # rlt: noqa[RLT002]  # expect[RLT000]
    # clean: a well-formed suppression (known rule + reason) is no
    # finding even where the suppressed rule never fired.
    c = float(x)  # rlt: noqa[RLT002] reasoned and well-formed
    return a, b, c


class Engine:
    def present_method(self):
        # clean: a qualname that resolves satisfies the drift check.
        return 1
