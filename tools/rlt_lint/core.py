"""rlt-lint core: the per-file AST checker and the repo configuration.

Everything here is stdlib-only (``ast`` + ``re``) so ``format.sh`` can
gate on it in environments with no lint tooling installed.  The checker
is one recursive walker per file with explicit lexical context (class
stack, function stack, ``with``-lock stack, dict-key stack); rules are
small predicates over that context.  See the package docstring for the
rule catalog and ``docs/STATIC_ANALYSIS.md`` for the policy.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

__all__ = [
    "RULES",
    "Config",
    "Finding",
    "check_source",
    "load_env_registry",
    "load_schema_keys",
    "repo_config",
]

RULES = {
    "RLT000": "lint infrastructure (suppressions, registry, baseline)",
    "RLT001": "per-call jax.jit/pjit construction on a hot path",
    "RLT002": "host-sync call inside a registered hot-loop body",
    "RLT003": "guarded attribute accessed outside its lock",
    "RLT004": "clock discipline (wall vs perf_counter vs jit purity)",
    "RLT005": "RLT_* env read missing from parallel/env_bus.py",
    "RLT006": "telemetry dict key not in the schema validator key set",
    "RLT007": "thread hygiene (daemon=, swallowed thread errors)",
    "RLT008": "module/class-scope jit bypassing the program ledger",
}


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str
    #: Stripped source text of the flagged line (the baseline match key).
    text: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass(frozen=True)
class Config:
    """Which files/functions each rule applies to.  Paths are
    repo-relative with forward slashes; qualnames are ``Class.method``
    for methods and bare names for module-level functions."""

    #: RLT001: functions where constructing a jit object is banned.
    hot_jit: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    #: RLT002: hot-loop bodies where host syncs are banned.
    hot_sync: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    #: RLT004d: files whose SpanTracer() sites must pass clock=.
    wall_clock_tracer_files: FrozenSet[str] = frozenset()
    #: RLT004a: per-process timing modules where time.time() is banned
    #: (dict values under a wall-timestamp key are exempt).
    perf_timing_files: FrozenSet[str] = frozenset()
    #: RLT004b: cross-process envelope modules banning perf_counter().
    trace_envelope_files: FrozenSet[str] = frozenset()
    #: RLT006: path -> {function qualname -> schema key-set prefix}.
    schema_producers: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: RLT006: prefix -> (required keys, optional keys).
    schema_keys: Dict[str, Tuple[FrozenSet[str], FrozenSet[str]]] = field(
        default_factory=dict
    )
    #: RLT005: registered env knob names (parallel/env_bus.py).
    env_registry: FrozenSet[str] = frozenset()
    #: RLT005: files whose literal RLT_* strings are the registry itself.
    env_exempt_files: FrozenSet[str] = frozenset()
    #: RLT008: path prefixes where import-time jit construction must
    #: route through telemetry.program_ledger.ledgered_jit.
    ledger_paths: Tuple[str, ...] = ()


# Wall-timestamp dict keys exempt from the RLT004a time.time() ban:
# cross-process envelopes NEED a shared epoch there.
_TS_KEYS = frozenset({"ts", "t_wall", "wall_ts", "send_ts"})

_JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit",
    "jax.experimental.pjit.pjit",
})
_SYNC_SIMPLE = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "jax.block_until_ready",
})
_ENV_GET = frozenset({
    "os.environ.get", "environ.get", "os.getenv", "getenv",
    "os.environ.setdefault", "environ.setdefault",
    "os.environ.pop", "environ.pop",
})
_ENV_MAPS = frozenset({"os.environ", "environ"})
# Banned namespaces inside jit-wrapped (trace-pure) functions: host
# clocks and host RNG burn into the compiled program at trace time.
_JIT_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")

_NOQA_RE = re.compile(
    r"#\s*rlt:\s*noqa\[([A-Za-z0-9_,\s]+)\]\s*(.*)$"
)
_GUARD_RE = re.compile(r"#\s*guarded by\s+(self\.\w+)")
_HOLDS_RE = re.compile(r"#\s*rlt:\s*holds\s+(self\.\w+)")


def _dotted(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` attribute/name chains; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _decorator_name(deco: ast.AST) -> Optional[str]:
    """Dotted name of a decorator, unwrapping the
    ``@partial(jax.jit, ...)`` idiom (required whenever static/donated
    args are used) to the wrapped callable's name."""
    if isinstance(deco, ast.Call):
        dname = _dotted(deco.func)
        if (dname or "").rsplit(".", 1)[-1] == "partial" and deco.args:
            return _dotted(deco.args[0])
        return dname
    return _dotted(deco)


class _Frame:
    """Per-function lexical state.  A nested def/lambda gets a FRESH
    frame: its body does not execute under the enclosing ``with`` locks
    (deferred execution), but it inherits hot-path membership (a
    closure defined in a hot loop runs in the hot loop)."""

    def __init__(self, node: Optional[ast.AST], hot_jit: bool,
                 hot_sync: bool, producer: Optional[str],
                 holds: FrozenSet[str], jit_pure: bool):
        self.node = node
        self.hot_jit = hot_jit
        self.hot_sync = hot_sync
        self.producer = producer          # schema prefix, RLT006
        self.locks_held: List[str] = list(holds)
        self.checked_dict_vars: Set[str] = set()
        self.jit_pure = jit_pure


class _FileChecker:
    def __init__(self, path: str, src: str, config: Config):
        self.path = path
        self.src = src
        self.config = config
        self.lines = src.splitlines()
        self.findings: List[Finding] = []
        # line -> (set of codes, reason)
        self.noqa: Dict[int, Tuple[Set[str], str]] = {}
        # def-line -> lock name the method asserts its caller holds
        self.holds: Dict[int, str] = {}
        # line -> guard lock name (collection pass uses it)
        self.guard_comment: Dict[int, str] = {}
        # (class qualname, attr) -> lock dotted name
        self.guards: Dict[Tuple[str, str], str] = {}
        # lines spanned by the annotated declaration assignments —
        # the ONLY accesses a guard comment itself exempts (a guard
        # comment pasted on a use site must not become a reason-free
        # suppression channel; that is what noqa-with-reason is for)
        self.guard_decl_lines: Set[int] = set()
        # function names wrapped by jax.jit/pjit somewhere in this file
        self.jit_wrapped: Set[str] = set()
        # function names used as threading.Thread target= in this file
        self.thread_targets: Set[str] = set()
        # first line of the statement currently being visited
        self._stmt_line: Optional[int] = None
        # RLT008 applies to this file at all (prefix-scoped)
        self._ledger_scope = any(
            path.startswith(p) for p in config.ledger_paths
        )
        self._parse_comments()

    # -- comments ------------------------------------------------------------
    def _comment_lines(self) -> Dict[int, str]:
        """line -> comment text, via tokenize — NOT raw line scanning:
        a docstring or error message *mentioning* ``# rlt: noqa[...]``
        (this package's own help text does) must not parse as a
        directive."""
        import io
        import tokenize

        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.src).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Unparseable source: run() reports the syntax error; no
            # directives apply.
            return {}
        return out

    def _parse_comments(self) -> None:
        for i, line in self._comment_lines().items():
            m = _NOQA_RE.search(line)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",")
                         if c.strip()}
                reason = m.group(2).strip()
                if reason.startswith("#"):
                    # a following comment is not a reason
                    reason = ""
                self.noqa[i] = (codes, reason)
                for code in codes:
                    if code not in RULES:
                        self._raw(i, "RLT000",
                                  f"noqa names unknown rule {code}")
                if not reason:
                    self._raw(
                        i, "RLT000",
                        "noqa without a reason — say why the rule does "
                        "not apply here",
                    )
            m = _GUARD_RE.search(line)
            if m:
                self.guard_comment[i] = m.group(1)
            m = _HOLDS_RE.search(line)
            if m:
                self.holds[i] = m.group(1)

    def _raw(self, line: int, rule: str, msg: str) -> None:
        text = (self.lines[line - 1].strip()
                if 0 < line <= len(self.lines) else "")
        self.findings.append(Finding(self.path, line, rule, msg, text))

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        """Record a finding unless a noqa for ``rule`` covers any line
        the node spans — or the first line of the enclosing statement
        (multi-line calls put the comment where the statement starts)."""
        lo = getattr(node, "lineno", 1)
        hi = getattr(node, "end_lineno", lo) or lo
        lines = set(range(lo, hi + 1))
        if self._stmt_line is not None:
            lines.add(self._stmt_line)
            # a standalone comment line directly above the statement
            above = self._stmt_line - 1
            if (0 < above <= len(self.lines)
                    and self.lines[above - 1].lstrip().startswith("#")):
                lines.add(above)
        for line in lines:
            entry = self.noqa.get(line)
            if entry and rule in entry[0] and entry[1]:
                return
        self._raw(lo, rule, msg)

    # -- entry ---------------------------------------------------------------
    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.src)
        except SyntaxError as e:
            self._raw(e.lineno or 1, "RLT000", f"syntax error: {e.msg}")
            return self.findings
        self._collect(tree)
        self._check_registry_drift(tree)
        frame = _Frame(None, False, False, None, frozenset(), False)
        self._visit_body(tree.body, [], frame, dict_key_stack=[])
        return self.findings

    # -- collection pass -----------------------------------------------------
    def _collect(self, tree: ast.Module) -> None:
        class_stack: List[str] = []

        def walk(node: ast.AST) -> None:
            if isinstance(node, ast.ClassDef):
                class_stack.append(node.name)
                for child in node.body:
                    walk(child)
                class_stack.pop()
                return
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                base = name.rsplit(".", 1)[-1]
                if name in _JIT_NAMES and node.args:
                    first = node.args[0]
                    if isinstance(first, ast.Name):
                        self.jit_wrapped.add(first.id)
                if base == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            tgt = _dotted(kw.value)
                            if tgt:
                                self.thread_targets.add(
                                    tgt.rsplit(".", 1)[-1]
                                )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if _decorator_name(deco) in _JIT_NAMES:
                        self.jit_wrapped.add(node.name)
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and class_stack:
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                lo = node.lineno
                hi = getattr(node, "end_lineno", lo) or lo
                lock = None
                # inline on any spanned line, or a standalone comment
                # line directly above the assignment
                candidates = list(range(lo, hi + 1))
                if (lo > 1 and self.lines[lo - 2].lstrip()
                        .startswith("#")):
                    candidates.append(lo - 1)
                for line in candidates:
                    if line in self.guard_comment:
                        lock = self.guard_comment[line]
                        break
                if lock is not None:
                    for tgt in targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            cls = ".".join(class_stack)
                            self.guards[(cls, tgt.attr)] = lock
                            self.guard_decl_lines.update(
                                range(lo, hi + 1)
                            )
            for child in ast.iter_child_nodes(node):
                walk(child)

        for top in tree.body:
            walk(top)

    def _check_registry_drift(self, tree: ast.Module) -> None:
        """A registered hot-path/producer qualname that no longer
        resolves means the protection silently vanished — fail loudly
        so the registry moves with the refactor."""
        defined: Set[str] = set()

        def walk(node: ast.AST, cls: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, cls + [child.name])
                elif isinstance(child,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defined.add(".".join(cls + [child.name]))
                    # nested defs are not registry targets
                else:
                    walk(child, cls)

        walk(tree, [])
        registered: Set[str] = set()
        registered |= set(self.config.hot_jit.get(self.path, ()))
        registered |= set(self.config.hot_sync.get(self.path, ()))
        registered |= set(
            self.config.schema_producers.get(self.path, {})
        )
        for qn in sorted(registered - defined):
            self._raw(
                1, "RLT000",
                f"registered qualname {qn!r} not found in {self.path} — "
                f"update tools/rlt_lint config to follow the refactor",
            )

    # -- checking pass -------------------------------------------------------
    def _qualname(self, class_stack: List[str], name: str) -> str:
        return ".".join(class_stack + [name])

    def _visit_body(self, body: List[ast.stmt], class_stack: List[str],
                    frame: _Frame, dict_key_stack: List[Optional[str]]
                    ) -> None:
        for stmt in body:
            self._visit(stmt, class_stack, frame, dict_key_stack)

    def _enter_function(self, node, class_stack: List[str],
                        frame: _Frame) -> _Frame:
        cfg = self.config
        qn = self._qualname(class_stack, node.name) \
            if frame.node is None else None
        hot_jit = frame.hot_jit or (
            qn is not None and qn in cfg.hot_jit.get(self.path, ())
        )
        hot_sync = frame.hot_sync or (
            qn is not None and qn in cfg.hot_sync.get(self.path, ())
        )
        producer = frame.producer or (
            cfg.schema_producers.get(self.path, {}).get(qn)
            if qn is not None else None
        )
        holds: Set[str] = set()
        lo = node.lineno
        if node.decorator_list:
            lo = min(lo, node.decorator_list[0].lineno)
        hi = node.body[0].lineno if node.body else node.lineno
        candidates = list(range(lo, hi + 1))
        # a standalone comment line directly above the def
        if lo > 1 and self.lines[lo - 2].lstrip().startswith("#"):
            candidates.append(lo - 1)
        for line in candidates:
            if line in self.holds:
                holds.add(self.holds[line])
        jit_pure = frame.jit_pure or node.name in self.jit_wrapped
        lru = any(
            (_dotted(d) or "").rsplit(".", 1)[-1] in ("lru_cache", "cache")
            or (isinstance(d, ast.Call)
                and (_dotted(d.func) or "").rsplit(".", 1)[-1]
                in ("lru_cache", "cache"))
            for d in node.decorator_list
        )
        new = _Frame(node, hot_jit and not lru, hot_sync, producer,
                     frozenset(holds), jit_pure)
        return new

    def _visit(self, node: ast.AST, class_stack: List[str], frame: _Frame,
               dict_key_stack: List[Optional[str]]) -> None:
        if isinstance(node, ast.stmt):
            self._stmt_line = node.lineno
        if isinstance(node, ast.ClassDef):
            self._visit_body(node.body, class_stack + [node.name],
                             frame, dict_key_stack)
            return

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if frame.hot_jit:
                # A @jax.jit-decorated def inside a hot function is a
                # fresh jit object per enclosing call, same as jit(f) —
                # and @partial(jax.jit, ...) constructs one just the
                # same (it is the required form for static/donated
                # args, so the most common evasion).
                for deco in node.decorator_list:
                    if _decorator_name(deco) in _JIT_NAMES:
                        self._flag(
                            deco, "RLT001",
                            "jit-decorated def inside a hot-path "
                            "function constructs a fresh jit object "
                            "per call — hoist it",
                        )
            # RLT008 — a @jax.jit def at module/class scope builds an
            # executable the program ledger never sees: no compile
            # timing, no cost/memory rows, and its recompiles are
            # invisible to the forensics ring.
            if frame.node is None and self._ledger_scope:
                for deco in node.decorator_list:
                    if _decorator_name(deco) in _JIT_NAMES:
                        self._flag(
                            deco, "RLT008",
                            "jit-decorated def at module/class scope "
                            "bypasses the program ledger — wrap with "
                            "telemetry.program_ledger.ledgered_jit("
                            "fn, site=...) so the executable is "
                            "inventoried and recompiles attributed",
                        )
            new = self._enter_function(node, class_stack, frame)
            # RLT007b: swallowed errors inside thread targets.
            if node.name in self.thread_targets:
                self._check_thread_body(node)
            self._visit_body(node.body, class_stack, new, [])
            return

        if isinstance(node, ast.Lambda):
            new = _Frame(node, frame.hot_jit, frame.hot_sync,
                         frame.producer, frozenset(), frame.jit_pure)
            self._visit(node.body, class_stack, new, [])
            return

        if isinstance(node, ast.With):
            added = []
            for item in node.items:
                name = _dotted(item.context_expr)
                if name is None and isinstance(item.context_expr, ast.Call):
                    name = _dotted(item.context_expr.func)
                if name:
                    frame.locks_held.append(name)
                    added.append(name)
                self._visit(item.context_expr, class_stack, frame,
                            dict_key_stack)
            self._visit_body(node.body, class_stack, frame, dict_key_stack)
            for _ in added:
                frame.locks_held.pop()
            return

        if isinstance(node, ast.Assign):
            self._check_dict_assign(node, frame)
            self._visit(node.value, class_stack, frame, dict_key_stack)
            for tgt in node.targets:
                self._visit(tgt, class_stack, frame, dict_key_stack)
            return

        if isinstance(node, ast.Dict):
            self._check_dict_literal(node, frame)
            for key, value in zip(node.keys, node.values):
                if key is not None:
                    self._visit(key, class_stack, frame, dict_key_stack)
                key_name = (key.value if isinstance(key, ast.Constant)
                            and isinstance(key.value, str) else None)
                dict_key_stack.append(key_name)
                self._visit(value, class_stack, frame, dict_key_stack)
                dict_key_stack.pop()
            return

        if isinstance(node, ast.Call):
            self._check_call(node, class_stack, frame, dict_key_stack)
            for child in ast.iter_child_nodes(node):
                self._visit(child, class_stack, frame, dict_key_stack)
            return

        if isinstance(node, ast.Subscript):
            self._check_subscript(node, frame)
            for child in ast.iter_child_nodes(node):
                self._visit(child, class_stack, frame, dict_key_stack)
            return

        if isinstance(node, ast.Attribute):
            self._check_guarded_attr(node, class_stack, frame)
            self._visit(node.value, class_stack, frame, dict_key_stack)
            return

        if isinstance(node, ast.ExceptHandler):
            # handled by _check_thread_body for thread targets; still
            # recurse for nested content.
            self._visit_body(node.body, class_stack, frame, dict_key_stack)
            return

        for child in ast.iter_child_nodes(node):
            self._visit(child, class_stack, frame, dict_key_stack)

    # -- rule bodies ---------------------------------------------------------
    def _check_call(self, node: ast.Call, class_stack: List[str],
                    frame: _Frame,
                    dict_key_stack: List[Optional[str]]) -> None:
        cfg = self.config
        name = _dotted(node.func) or ""
        base = name.rsplit(".", 1)[-1]
        kwargs = {kw.arg for kw in node.keywords}

        # RLT008 — jit construction at module/class scope (import
        # time).  These are exactly the steady-state executables the
        # program ledger exists to inventory; a bare jit here dispatches
        # outside the ledger forever.  ``partial(jax.jit, ...)`` alone
        # is a factory, not a program — only flag when a function is
        # actually wrapped (direct call or the partial applied).
        if frame.node is None and self._ledger_scope and node.args:
            wrapped = name if name in _JIT_NAMES else None
            if wrapped is None and isinstance(node.func, ast.Call):
                inner = _decorator_name(node.func)
                if inner in _JIT_NAMES:
                    wrapped = inner
            if wrapped is not None:
                self._flag(
                    node, "RLT008",
                    f"bare {wrapped}() at module/class scope bypasses "
                    f"the program ledger — route through "
                    f"telemetry.program_ledger.ledgered_jit(fn, "
                    f"site=...) so compile time, cost/memory and "
                    f"recompile forensics are captured",
                )

        # RLT001 — jit construction on a hot path.
        if frame.hot_jit and name in _JIT_NAMES:
            self._flag(
                node, "RLT001",
                "jit object constructed per call on a hot path — build "
                "it at module level, cache it on self at init, or "
                "functools.lru_cache the factory (a fresh jax.jit "
                "re-triggers backend_compile under cache pressure)",
            )

        # RLT002 — host syncs inside registered hot-loop bodies.
        if frame.hot_sync:
            sync = None
            if name in _SYNC_SIMPLE:
                sync = name
            elif base in ("item", "block_until_ready") and "." in name:
                sync = name
            elif name in ("float", "int") and node.args and not all(
                isinstance(a, ast.Constant) for a in node.args
            ):
                sync = name
            if sync is not None:
                self._flag(
                    node, "RLT002",
                    f"{sync}() forces a host/device sync inside a "
                    f"registered hot-loop body — keep the value on "
                    f"device, fetch asynchronously (_AsyncLogFetch "
                    f"pattern), or annotate the deliberate sync",
                )

        # RLT004a — wall clock in per-process timing modules.
        if (name == "time.time"
                and self.path in cfg.perf_timing_files
                and not (dict_key_stack and dict_key_stack[-1]
                         in _TS_KEYS)):
            self._flag(
                node, "RLT004",
                "time.time() in a perf-timing module — durations and "
                "phase timing use time.perf_counter(); wall clock is "
                "for cross-process envelope 'ts' fields only",
            )

        # RLT004b — perf_counter in cross-process envelope modules.
        if (name == "time.perf_counter"
                and self.path in cfg.trace_envelope_files):
            self._flag(
                node, "RLT004",
                "time.perf_counter() in a trace-envelope module — "
                "cross-process timestamps need the shared wall-clock "
                "epoch (time.time)",
            )

        # RLT004c — host clocks/RNG inside jit-wrapped functions.
        if frame.jit_pure and name.startswith(_JIT_IMPURE_PREFIXES):
            self._flag(
                node, "RLT004",
                f"{name}() inside a jit-wrapped function — the value "
                f"burns in at trace time (use traced operands or "
                f"jax.random with a threaded key)",
            )

        # RLT004d — distributed tracers must pass the wall clock.
        if (base == "SpanTracer"
                and self.path in cfg.wall_clock_tracer_files
                and "clock" not in kwargs):
            self._flag(
                node, "RLT004",
                "SpanTracer() without clock= in a distributed-tracer "
                "module — cross-process spans need clock=time.time or "
                "stitched traces land on process-private epochs",
            )

        # RLT005 — env reads must be registered.
        if (name in _ENV_GET and node.args
                and self.path not in cfg.env_exempt_files):
            first = node.args[0]
            if (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith("RLT_")
                    and first.value not in cfg.env_registry):
                self._flag(
                    node, "RLT005",
                    f"env knob {first.value} is not registered in "
                    f"parallel/env_bus.py — unregistered knobs are "
                    f"never forwarded to workers",
                )

        # RLT007a — explicit daemon= on every Thread.
        if base == "Thread" and "daemon" not in kwargs:
            self._flag(
                node, "RLT007",
                "threading.Thread without explicit daemon= — decide "
                "(and document) whether this thread may outlive its "
                "owner",
            )

        # RLT006 — subscript-store producers handled in _check_subscript;
        # nothing to do for calls.

    def _check_subscript(self, node: ast.Subscript, frame: _Frame) -> None:
        cfg = self.config
        name = _dotted(node.value)
        # RLT005 — os.environ["RLT_X"] forms.
        if (name in _ENV_MAPS
                and self.path not in cfg.env_exempt_files
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
                and node.slice.value.startswith("RLT_")
                and node.slice.value not in cfg.env_registry):
            self._flag(
                node, "RLT005",
                f"env knob {node.slice.value} is not registered in "
                f"parallel/env_bus.py — unregistered knobs are never "
                f"forwarded to workers",
            )
        # RLT006 — var["key"] stores on a checked producer dict.
        if (frame.producer is not None
                and isinstance(node.value, ast.Name)
                and node.value.id in frame.checked_dict_vars
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            self._check_schema_key(node, frame.producer, node.slice.value)

    def _check_dict_assign(self, node: ast.Assign, frame: _Frame) -> None:
        """Track names bound to checked producer dicts so later
        ``name["key"] = ...`` stores are validated too."""
        if frame.producer is None:
            return
        if isinstance(node.value, ast.Dict) and (
                self._anchored(node.value)
                or frame.producer.endswith("!any")):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    frame.checked_dict_vars.add(tgt.id)

    def _anchored(self, node: ast.Dict) -> bool:
        """A producer dict literal is checked when it carries the wire
        anchor key (``type``/``schema``) or the producer covers every
        dict (single-document builders)."""
        for key in node.keys:
            if (isinstance(key, ast.Constant)
                    and key.value in ("type", "schema")):
                return True
        return False

    def _check_dict_literal(self, node: ast.Dict, frame: _Frame) -> None:
        if frame.producer is None:
            return
        prefix = frame.producer
        anchored = self._anchored(node) or prefix.endswith("!any")
        if not anchored:
            return
        for key in node.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                self._check_schema_key(key, prefix, key.value)

    def _check_schema_key(self, node: ast.AST, prefix: str,
                          key: str) -> None:
        prefix = prefix.split("!", 1)[0]
        sets = self.config.schema_keys.get(prefix)
        if sets is None:
            self._flag(
                node, "RLT000",
                f"producer registered against unknown schema prefix "
                f"{prefix!r} — no _{prefix}_REQUIRED/_OPTIONAL in "
                f"telemetry/schema.py",
            )
            return
        required, optional = sets
        if key not in required and key not in optional:
            self._flag(
                node, "RLT006",
                f"dict key {key!r} is not in telemetry/schema.py's "
                f"_{prefix}_REQUIRED/_OPTIONAL sets — producer and "
                f"validator drifted",
            )

    def _check_guarded_attr(self, node: ast.Attribute,
                            class_stack: List[str], frame: _Frame) -> None:
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self" and class_stack):
            return
        cls = ".".join(class_stack)
        lock = self.guards.get((cls, node.attr))
        if lock is None:
            return
        fn = frame.node
        fn_name = getattr(fn, "name", None)
        if fn_name in ("__init__", "__del__"):
            return
        # the annotated declaration assignment itself — and ONLY it; a
        # guard comment on a use site is not a suppression (use
        # `# rlt: noqa[RLT003] reason` for that)
        if node.lineno in self.guard_decl_lines:
            return
        if lock in frame.locks_held:
            return
        self._flag(
            node, "RLT003",
            f"self.{node.attr} is '# guarded by {lock}' but accessed "
            f"outside 'with {lock}' — wrap the access or annotate the "
            f"method '# rlt: holds {lock}'",
        )

    def _check_thread_body(self, node) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            if sub.type is None:
                self._flag(
                    sub, "RLT007",
                    "bare except inside a thread target — name the "
                    "exception types; a typo-level bug would die "
                    "silently on this thread",
                )
                continue
            tname = _dotted(sub.type) or ""
            body_is_pass = all(
                isinstance(s, ast.Pass) for s in sub.body
            )
            if (tname.rsplit(".", 1)[-1] in ("Exception", "BaseException")
                    and body_is_pass):
                self._flag(
                    sub, "RLT007",
                    f"except {tname}: pass inside a thread target "
                    f"swallows every failure on this thread — log it, "
                    f"poison a mailbox, or narrow the type",
                )


def check_source(path: str, src: str, config: Config) -> List[Finding]:
    """Lint one file's source; returns findings (noqa already applied,
    baseline NOT applied — the CLI layers that)."""
    return _FileChecker(path, src, config).run()


# ---------------------------------------------------------------------------
# Repo configuration (registries + loaders)
# ---------------------------------------------------------------------------

def load_env_registry(env_bus_src: str) -> FrozenSet[str]:
    """Parse ``parallel/env_bus.py`` *statically* (no import): every
    ``EnvKnob("NAME", ...)`` call's literal first argument."""
    names: Set[str] = set()
    tree = ast.parse(env_bus_src)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and (_dotted(node.func) or "").rsplit(".", 1)[-1]
                == "EnvKnob"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            names.add(node.args[0].value)
    return frozenset(names)


def load_schema_keys(
    schema_src: str,
) -> Dict[str, Tuple[FrozenSet[str], FrozenSet[str]]]:
    """Parse ``telemetry/schema.py``'s module-level
    ``_<PREFIX>_REQUIRED`` / ``_<PREFIX>_OPTIONAL`` dict literals into
    per-prefix key sets."""
    req: Dict[str, Set[str]] = {}
    opt: Dict[str, Set[str]] = {}
    pat = re.compile(r"^_(\w+)_(REQUIRED|OPTIONAL)$")
    tree = ast.parse(schema_src)
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Dict)):
            continue
        m = pat.match(node.targets[0].id)
        if not m:
            continue
        keys = {
            k.value for k in node.value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        (req if m.group(2) == "REQUIRED" else opt).setdefault(
            m.group(1), set()
        ).update(keys)
    out: Dict[str, Tuple[FrozenSet[str], FrozenSet[str]]] = {}
    for prefix in set(req) | set(opt):
        out[prefix] = (
            frozenset(req.get(prefix, ())),
            frozenset(opt.get(prefix, ())),
        )
    return out


_PKG = "ray_lightning_tpu"

#: RLT001 — no jit construction inside these (request/step/tick paths).
_HOT_JIT = {
    f"{_PKG}/serve/engine.py": frozenset({
        "ServeEngine.step", "ServeEngine._decode_tick",
        "ServeEngine._spec_tick", "ServeEngine._tick_widths",
        "ServeEngine._tick_top_ks", "ServeEngine._complete",
        "ServeEngine._handle_queue_request",
        # Multi-LoRA hot paths: per-tick operand assembly and the
        # queue-plane hot-add (the round-17 fresh-jit-per-request
        # footgun must stay mechanically impossible here — the pool's
        # ONE scatter program is built at AdapterPool.__init__).
        "ServeEngine._lora_operands", "ServeEngine.add_adapter",
        "ServeEngine._load_adapter_item",
        # Prefix-cache / chunked-prefill hot paths: claims are pure
        # refcount bumps and chunk ticks replay ONE pre-built program
        # per step — a fresh jit on any of these would recompile per
        # admission.
        "ServeEngine._claim_prefix", "ServeEngine._suffix_prefill",
        "ServeEngine._start_chunk_job", "ServeEngine._chunk_tick",
        "ServeEngine._prefix_insert",
        # Live-migration admission: importing a mid-flight sequence
        # must reuse the SAME greedy-decomposed _import_fn executables
        # the handoff path warmed — a fresh jit here would turn every
        # drain into a recompile storm on the survivor.
        "ServeEngine._admit_migration",
    }),
    f"{_PKG}/serve/lora.py": frozenset({
        "AdapterPool.add", "AdapterPool.remove", "AdapterPool.slot_of",
    }),
    f"{_PKG}/serve/dist/prefill.py": frozenset({
        "PrefillRunner.step", "PrefillRunner._process",
    }),
    f"{_PKG}/serve/dist/router.py": frozenset({
        "Router.submit_request", "Router._route",
        "Router._ensure_adapter",
        # Headroom tie-break rides the placement hot path: the key
        # function must stay a pure dict read, never a jit probe.
        "Router._headroom",
        # Serving-plane resilience (ISSUE 19): migration retarget,
        # hedged placement and the brownout gate all ride the poll /
        # submit hot loops — pure dict work only.
        "Router._on_migration", "Router._hedge",
        "Router._update_brownout",
    }),
    f"{_PKG}/mpmd/stage.py": frozenset({
        "StageRunner._run_opt_step",
    }),
    f"{_PKG}/mpmd/transfer.py": frozenset({
        # The quantized-wire codec runs per micro-batch SEND on every
        # pipeline step: host-side numpy by design (np.asarray is its
        # job), but a jit constructed here would recompile per frame.
        "WireCodec.encode_payload", "LocalChannel.send",
        "QueueChannel.send", "StageInbox._file",
    }),
    f"{_PKG}/parallel/overlap.py": frozenset({
        # Grad taps are built per TRACE (amortized by the ledger's jit
        # cache), never per step — a jax.jit inside the tap machinery
        # would defeat exactly the overlap the taps exist to create.
        "TapPlane.tap", "TapPlane.apply_entry_taps",
    }),
    f"{_PKG}/core/loop.py": frozenset({
        "_AsyncLogFetch.schedule", "_RunningMeanLogs.update",
        "_RunningMeanLogs.update_stride", "_place_batch",
    }),
}

#: RLT002 — no host syncs inside these hot-loop bodies.  Narrower than
#: _HOT_JIT: prefill/router do host work by design (jax-free or
#: export-to-host), so only the decode/step/instruction loops gate.
_HOT_SYNC = {
    f"{_PKG}/serve/engine.py": frozenset({
        "ServeEngine.step", "ServeEngine._decode_tick",
        "ServeEngine._spec_tick", "ServeEngine._lora_operands",
        # Chunk ticks interleave with decode: a host sync per chunk
        # (beyond the final-chunk TTFT sync, which carries a noqa)
        # would serialize the stream the no-stall contract protects.
        "ServeEngine._claim_prefix", "ServeEngine._suffix_prefill",
        "ServeEngine._chunk_tick",
    }),
    f"{_PKG}/mpmd/stage.py": frozenset({
        "StageRunner._run_opt_step",
    }),
    f"{_PKG}/core/loop.py": frozenset({
        "_AsyncLogFetch.schedule", "_RunningMeanLogs.update",
        "_RunningMeanLogs.update_stride",
    }),
}

#: RLT006 — wire-document builders cross-checked against schema.py.
_SCHEMA_PRODUCERS = {
    f"{_PKG}/telemetry/heartbeat.py": {"make_beat": "HEARTBEAT"},
    f"{_PKG}/telemetry/monitor.py": {"make_event": "EVENT"},
    f"{_PKG}/telemetry/logs.py": {"make_log_item": "LOG"},
    f"{_PKG}/telemetry/spans.py": {"SpanTracer._span_dict": "SPAN!any"},
    f"{_PKG}/serve/dist/handoff.py": {
        "request_fields": "SERVE_REQUEST",
        "make_handoff_item": "SERVE_HANDOFF",
        "make_adapter_load_item": "SERVE_ADAPTER_LOAD",
        "make_migration_item": "SERVE_MIGRATION",
    },
    # SLO & capacity plane (ISSUE 18): store points, alert detail,
    # the oracle snapshot and the router's fleet fold.
    f"{_PKG}/telemetry/timeseries.py": {
        "TimeSeriesStore.points": "TIMESERIES_POINT",
    },
    f"{_PKG}/telemetry/slo.py": {
        "_alert_detail": "SLO_ALERT_DETAIL!any",
    },
    f"{_PKG}/serve/capacity.py": {
        "CapacityOracle.snapshot": "CAPACITY_SNAPSHOT",
        "aggregate_fleet": "FLEET_CAPACITY!any",
    },
}


def repo_config(repo_root: str) -> Config:
    """The tree's live configuration: registries above + key sets and
    the env registry parsed from their source-of-truth modules."""
    import os

    schema_path = os.path.join(repo_root, _PKG, "telemetry", "schema.py")
    env_bus_path = os.path.join(repo_root, _PKG, "parallel", "env_bus.py")
    with open(schema_path) as f:
        schema_keys = load_schema_keys(f.read())
    with open(env_bus_path) as f:
        env_registry = load_env_registry(f.read())
    return Config(
        hot_jit=_HOT_JIT,
        hot_sync=_HOT_SYNC,
        wall_clock_tracer_files=frozenset({
            f"{_PKG}/serve/engine.py",
            f"{_PKG}/serve/dist/router.py",
            f"{_PKG}/serve/dist/prefill.py",
            f"{_PKG}/mpmd/stage.py",
        }),
        perf_timing_files=frozenset({
            f"{_PKG}/telemetry/spans.py",
            f"{_PKG}/telemetry/step_stats.py",
            f"{_PKG}/telemetry/timeseries.py",
            f"{_PKG}/telemetry/slo.py",
            f"{_PKG}/serve/capacity.py",
            f"{_PKG}/serve/scheduler.py",
            f"{_PKG}/serve/metrics.py",
            # Brownout dwell/probe timers and client retry/hedge
            # latency samples are per-process intervals: monotonic
            # only, never wall clock.
            f"{_PKG}/serve/brownout.py",
            f"{_PKG}/serve/client.py",
            f"{_PKG}/mpmd/transfer.py",
            f"{_PKG}/parallel/grad_sync.py",
            f"{_PKG}/core/loop.py",
            f"{_PKG}/core/callbacks.py",
        }),
        trace_envelope_files=frozenset({
            f"{_PKG}/telemetry/propagate.py",
        }),
        schema_producers=_SCHEMA_PRODUCERS,
        schema_keys=schema_keys,
        env_registry=env_registry,
        env_exempt_files=frozenset({
            f"{_PKG}/parallel/env_bus.py",
        }),
        # RLT008 — the whole package: every import-time executable must
        # land in the program ledger (tools/bench drivers may build
        # throwaway jits; the package's are the steady-state programs).
        ledger_paths=(f"{_PKG}/",),
    )
