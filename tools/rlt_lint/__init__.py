"""rlt-lint: AST-based invariant checker for this repo's hot-path,
lock, clock, env-bus, schema and thread disciplines.

The rules mechanize recurring review findings (docs/STATIC_ANALYSIS.md
carries the catalog and the historical bug each rule encodes):

======= ================================================================
RLT001  per-call ``jax.jit``/``pjit`` construction on a hot path
RLT002  host-sync calls inside registered hot-loop bodies
RLT003  ``# guarded by self._lock`` attributes accessed outside the lock
RLT004  clock discipline (wall vs perf_counter vs jit-pure step fns)
RLT005  unregistered ``RLT_*`` env reads (``parallel/env_bus.py``)
RLT006  telemetry dict-literal keys vs ``telemetry/schema.py`` key sets
RLT007  thread hygiene (implicit ``daemon``, swallowed thread errors)
RLT000  lint infrastructure (bad suppressions, registry/baseline drift)
======= ================================================================

Zero dependencies beyond the stdlib ``ast`` module; runnable standalone
(``python -m tools.rlt_lint [--changed|--all]``) and wired into
``format.sh`` as layer 6.  Suppress a single line with
``# rlt: noqa[RLT00x] <reason>`` — the reason is mandatory.
"""

from tools.rlt_lint.core import (  # noqa: F401
    Config,
    Finding,
    check_source,
    load_env_registry,
    load_schema_keys,
    repo_config,
)
