import sys

from tools.rlt_lint.cli import main

sys.exit(main())
