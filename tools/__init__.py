"""Repo tooling namespace (``python -m tools.rlt_lint``)."""
