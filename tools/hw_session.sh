#!/usr/bin/env bash
# One-shot TPU measurement session: run when the axon tunnel is up.
# Captures, in order: device probe, headline bench, per-op profile,
# long-context bench, CE block sweep. Each stage logs to tools/hw_logs/.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tools/hw_logs
stamp=$(date +%Y%m%d_%H%M%S)
log() { echo "== $1 =="; }

log "probe"
timeout 120 python -c "import jax; print(jax.devices())" \
  2>&1 | tail -2 | tee "tools/hw_logs/${stamp}_probe.log" || {
    echo "TPU unreachable; aborting session"; exit 1; }

log "bench.py (headline)"
timeout 1800 python bench.py 2>&1 | tee "tools/hw_logs/${stamp}_bench.log"

log "profile_step (op breakdown)"
timeout 1800 python tools/profile_step.py --steps 6 \
  2>&1 | tee "tools/hw_logs/${stamp}_profile.log"

log "bench_long_context"
timeout 1800 python bench_long_context.py \
  2>&1 | tee "tools/hw_logs/${stamp}_longctx.log"

log "sweep_ce_blocks"
timeout 2400 python tools/sweep_ce_blocks.py \
  2>&1 | tee "tools/hw_logs/${stamp}_sweep.log"

log "kernel A/B: CE off"
RLT_DISABLE_KERNELS=ce timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_no_ce.log"

log "kernel A/B: LN off"
RLT_DISABLE_KERNELS=ln timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_no_ln.log"

log "kernel A/B: CE+LN off"
RLT_DISABLE_KERNELS=ce,ln timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_no_ce_ln.log"

log "remat A/B: drop flash_q/k/v saves (double-save hypothesis)"
RLT_REMAT_POLICY=dots+flash-out timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_remat_flashout.log"

log "remat A/B: bf16 scan-residual carry (residual-save diet)"
RLT_REMAT_POLICY=bf16-resid timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_remat_bf16resid.log"

log "opt-state A/B: block-scaled int8 AdamW moments"
RLT_OPT_STATE_DTYPE=int8 timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_opt_int8.log"

log "opt-state A/B: bf16 AdamW moments"
RLT_OPT_STATE_DTYPE=bfloat16 timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_opt_bf16.log"

log "update-sharding A/B: cross-replica sharded weight update"
RLT_UPDATE_SHARDING=on timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_update_shard.log"

log "combined diet: int8 state + sharded update + bf16 residuals"
RLT_OPT_STATE_DTYPE=int8 RLT_UPDATE_SHARDING=on \
RLT_REMAT_POLICY=bf16-resid timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_hbm_diet.log"

log "serve A/B: speculative decoding K sweep (spec_decode block)"
for k in 2 4 8; do
  RLT_SPEC_K=$k RLT_DISAGG_REPLICAS=0 timeout 1800 python bench_serve.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_spec_k${k}.log"
done

log "serve A/B: request-tracing overhead, cheap tier on/off (trace block)"
# bench_serve phase 6 runs the traced-vs-untraced closed-loop A/B and
# the inproc-fleet stitch-coverage probe internally; on real chips the
# overhead number is the one that matters (spans are host-side dict
# records racing ~ms device steps instead of ~100ms CPU steps).
RLT_DISAGG_REPLICAS=0 timeout 1800 python bench_serve.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_trace.log"

log "serve A/B: multi-tenant LoRA — Pallas BGMV vs XLA gather, multiplexed vs merge-and-swap (multi_lora block)"
# Adapter-count sweep x BGMV-arm A/B on real chips: phase 7 runs the
# N-tenant multiplexed pool against the merge-and-swap baseline with
# recompile counters pinned 0 in both arms; RLT_LORA_BGMV forces the
# kernel arm (pallas = scalar-prefetched per-row DMA of only the
# selected adapter's factors; xla = gathered einsum fallback) so the
# two logs isolate the kernel win at each tenant count.
for n in 8 64; do
  for impl in xla pallas; do
    RLT_MAX_ADAPTERS=$n RLT_LORA_BGMV=$impl RLT_DISAGG_REPLICAS=0 \
      timeout 2400 python bench_serve.py \
      2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_lora_n${n}_${impl}.log"
  done
done

log "serve A/B: disaggregated fleet vs monolith (serve_disagg block)"
# Replica-count sweep on real chips: each decode replica + prefill
# worker owns its own device set, so (unlike the contended CPU arm)
# vs_monolith here measures genuine horizontal scaling + the
# prefill/decode interference win; the chaos arm's kill-a-replica
# failover numbers come with each run.
for n in 2 4; do
  RLT_DISAGG_REPLICAS=$n RLT_DISAGG_PREFILL=1 timeout 2400 \
    python bench_serve.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_disagg_r${n}.log"
done

log "serve A/B: prefix-cache hit-rate sweep (prefix_cache block)"
# Phase 8 runs the cached-vs-cold TTFT A/B on a shared-prefix mix with
# token parity and both recompile counters pinned 0.  The shared-prefix
# share of the mix scans the hit-rate axis: the TTFT win should rise
# with the share (claimed blocks skip real TPU prefill flops here, not
# just CPU dispatch), and the 0-share arm bounds the index overhead.
for share in 25 50 90; do
  RLT_PREFIX_SHARE=$share RLT_DISAGG_REPLICAS=0 timeout 1800 \
    python bench_serve.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_prefix_s${share}.log"
done

log "serve A/B: chunked prefill width sweep (chunked_prefill block)"
# Long-prompt admission vs resident decode traffic at real sequence
# lengths: the no-stall bound (resident_max_stall_ticks <= 1) must
# hold at every width, and the width trades TTFT of the long prompt
# against per-tick decode latency — the sweep finds the knee.
for w in 512 1024 2048; do
  RLT_PREFILL_CHUNK=$w timeout 1800 python bench_long_context.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_longctx_chunk_w${w}.log"
done

log "program ledger: TPU cost/memory inventory + overhead A/B (programs block)"
# On real chips the ledger's cost_analysis FLOPs and memory_analysis
# HBM rows come from the TPU compiler (the numbers the roofline MFU
# cross-check and hbm_report size against — CPU runs only validate
# plumbing); bench.py's internal A/B re-times the cheap-tier headline
# with RLT_PROGRAM_LEDGER=0 vs 1, and the dispatch overhead must stay
# below noise against ~ms device steps.  The explicit off-arm run
# gives the whole-session sanity check that the observatory never
# shows up in the headline.
timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_ledger_on.log"
RLT_PROGRAM_LEDGER=0 timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_ledger_off.log"

log "serve SLO & capacity: saturation-knee calibration + burn-rate alerts (slo block)"
# Phase 9 predicts the saturation knee from a cold 0.5x Poisson arm
# (measured decode-tick + admission costs, serve/capacity.py), then
# measures it with a hot 1.5x arm and gates on prediction error —
# real-chip tick costs are ~ms, so this is where the oracle's fit and
# the <2% plane-overhead A/B actually earn their numbers.  The second
# run doubles the store interval to confirm the fit is bin-width
# robust on hardware.
RLT_SLO=1 RLT_CAPACITY=1 RLT_DISAGG_REPLICAS=0 timeout 1800 \
  python bench_serve.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_slo.log"
RLT_SLO=1 RLT_CAPACITY=1 RLT_TS_INTERVAL_S=0.5 RLT_DISAGG_REPLICAS=0 \
  timeout 1800 python bench_serve.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_serve_slo_halfbin.log"

log "comm/compute overlap A/B: backward-overlapped grad sync (comm_overlap block)"
# Trunk-segment sweep x wire-width A/B on real DCN: G=0 is the step-end
# baseline, G in {1,2,4} moves each segment's bucket all-reduce into the
# backward where XLA's latency-hiding scheduler can bury it.  The int8_ef
# arms compound the width cut with the schedule change (the headline
# claim); the full-width arms isolate pure overlap (segmentation must be
# bitwise-neutral there, so any tokens/s delta is schedule, not numerics).
for g in 1 2 4; do
  RLT_GRAD_OVERLAP=$g timeout 1800 python bench.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_overlap_g${g}_full.log"
  RLT_GRAD_OVERLAP=$g RLT_GRAD_COMM=int8_ef timeout 1800 python bench.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_overlap_g${g}_int8ef.log"
done
RLT_GRAD_COMM=int8_ef timeout 1800 python bench.py \
  2>&1 | tee "tools/hw_logs/${stamp}_bench_overlap_g0_int8ef.log"

log "MPMD wire A/B: quantized DCN activation transfers (mpmd xfer stats)"
# Pipeline-stage payload width against the f32 wire: bf16 halves the
# activation bytes with rounding only; int8 is the block-scaled codec
# (~3.9x) with sender-side EF on the grad direction.  On real DCN the
# xfer wire_ratio comes with measured step time, so these logs price
# the bandwidth cut against the host-side codec cost.
for wd in bf16 int8 "act:bf16,grad:int8"; do
  tag=$(echo "$wd" | tr ':,' '__')
  RLT_MPMD_WIRE_DTYPE=$wd timeout 1800 python bench.py \
    2>&1 | tee "tools/hw_logs/${stamp}_bench_mpmd_wire_${tag}.log"
done

log "done — logs in tools/hw_logs/${stamp}_*.log"
