"""Chaos sweep: run the fault matrix end-to-end and print a recovery
scorecard.

Two modes:

* ``--selftest`` (wired into ``format.sh`` layer 5): fast,
  subprocess-free checks of the chaos plane itself — the ``RLT_FAULT``
  grammar, deterministic (point, rank, step, nth) matching,
  exactly-once markers, the torn/bit-flip file corruptors, and the
  checkpoint verifier catching what they break.  Seconds, zero
  accelerator work.
* default: the full acceptance matrix — for each fault kind a real
  multi-process fit (worker actors on the CPU-simulated mesh) with the
  fault injected deterministically, asserting the fit completes with
  the correct final step count and the right recovery events.  This is
  the same matrix ``tests/test_fault_tolerance.py`` runs under pytest
  (``-m chaos``); the tool form prints a scorecard and exits non-zero
  on any unrecovered scenario.

Usage::

    python tools/chaos_sweep.py --selftest
    python tools/chaos_sweep.py                  # full matrix, 1 worker
    python tools/chaos_sweep.py --workers 2      # multi-process mesh
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# --selftest: the chaos plane itself (no subprocesses, no jax fits)
# ---------------------------------------------------------------------------

def _selftest() -> list:
    problems: list = []

    def check(cond: bool, what: str) -> None:
        if not cond:
            problems.append(what)

    from ray_lightning_tpu.fault import inject

    # Grammar round-trip.
    specs = inject.parse_faults(
        "crash@step:7,rank:1;hang@step:5,secs:120;"
        "bitflip@point:ckpt_write,nth:2;sigterm@step:3,once:0"
    )
    check(len(specs) == 4, "grammar: expected 4 specs")
    check(specs[0].kind == "crash" and specs[0].step == 7
          and specs[0].rank == 1, "grammar: crash spec fields")
    check(specs[1].secs == 120.0, "grammar: secs parse")
    check(specs[2].point == "ckpt_write" and specs[2].nth == 2,
          "grammar: point/nth parse")
    check(specs[3].once is False, "grammar: once:0 parse")
    for bad in ("explode@step:1", "crash@step", "crash@wat:1",
                "crash@point:nowhere"):
        try:
            inject.parse_faults(bad)
            problems.append(f"grammar: {bad!r} should not parse")
        except ValueError:
            pass

    # Deterministic matching + exactly-once markers.
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_") as tmp:
        plan = inject.FaultPlan(
            inject.parse_faults("exc@step:2,rank:0"), tmp
        )
        check(not plan.due("step", rank=0, step=1, epoch=0),
              "match: wrong step fired")
        check(not plan.due("step", rank=1, step=2, epoch=0),
              "match: wrong rank fired")
        due = plan.due("step", rank=0, step=2, epoch=0)
        check(len(due) == 1, "match: exact coordinates did not fire")
        plan.mark_fired(due[0])
        check(not plan.due("step", rank=0, step=2, epoch=0),
              "once: refired after marker")
        fresh = inject.FaultPlan(
            inject.parse_faults("exc@step:2,rank:0"), tmp
        )
        check(not fresh.due("step", rank=0, step=2, epoch=0),
              "once: marker did not survive a new plan (restart)")

        # nth occurrence counting.
        plan2 = inject.FaultPlan(
            inject.parse_faults("torn@point:ckpt_write,nth:2"), None
        )
        check(not plan2.due("ckpt_write", None, None, None),
              "nth: first occurrence fired")
        check(len(plan2.due("ckpt_write", None, None, None)) == 1,
              "nth: second occurrence did not fire")

        # Corruptors vs the checkpoint verifier.
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
            verify_stream_file,
        )

        import numpy as np

        path = os.path.join(tmp, "ck.ckpt")
        state_stream_to_file(
            to_state_stream({"w": np.arange(64, dtype=np.float32)}), path
        )
        check(verify_stream_file(path) == [], "verify: pristine flagged")
        inject._corrupt_bitflip(path)
        check(bool(verify_stream_file(path)), "verify: bitflip missed")
        state_stream_to_file(
            to_state_stream({"w": np.arange(64, dtype=np.float32)}), path
        )
        inject._corrupt_torn(path)
        check(bool(verify_stream_file(path)), "verify: torn missed")

    # Serve-plane grammar (docs/FAULT_TOLERANCE.md "Serving-plane
    # faults"): injection points, member/rid pins, send-site kinds.
    specs = inject.parse_faults(
        "blackhole@point:beat,replica:decode-0;"
        "torn@point:handoff_send,worker:prefill-0,nth:2;"
        "shm_vanish@point:handoff_send,rid:abc123;"
        "slow@point:replica_tick,replica:decode-1,secs:0.5,once:0;"
        "exc@point:adapter_load;"
        "blackhole@point:handoff_read,replica:decode-1"
    )
    check(len(specs) == 6, "serve grammar: expected 6 specs")
    check(specs[0].kind == "blackhole" and specs[0].replica == "decode-0",
          "serve grammar: replica pin parse")
    check(specs[1].worker == "prefill-0" and specs[1].nth == 2,
          "serve grammar: worker/nth parse")
    check(specs[2].kind == "shm_vanish" and specs[2].rid == "abc123",
          "serve grammar: rid pin parse")
    check(specs[3].kind == "slow" and specs[3].secs == 0.5
          and specs[3].once is False, "serve grammar: slow secs/once")
    check(specs[4].point == "adapter_load",
          "serve grammar: adapter_load point")
    for bad in ("blackhole@point:nowhere", "crash@replica",
                "wormhole@point:beat"):
        try:
            inject.parse_faults(bad)
            problems.append(f"serve grammar: {bad!r} should not parse")
        except ValueError:
            pass

    # Member-pinned matching: a replica pin must fire only for that
    # member, a rid pin only for that request, and the thread-local
    # member context must scope fire() to the declaring thread.
    plan3 = inject.FaultPlan(
        inject.parse_faults(
            "blackhole@point:beat,replica:decode-0;"
            "exc@point:handoff_read,rid:r-7"
        ),
        None,
    )
    check(not plan3.due("beat", None, None, None, replica="decode-1"),
          "serve match: wrong replica fired")
    check(len(plan3.due("beat", None, None, None,
                        replica="decode-0")) == 1,
          "serve match: pinned replica did not fire")
    check(not plan3.due("handoff_read", None, None, None,
                        replica="decode-0", rid="r-8"),
          "serve match: wrong rid fired")
    check(len(plan3.due("handoff_read", None, None, None,
                        replica="decode-0", rid="r-7")) == 1,
          "serve match: pinned rid did not fire")

    # End-to-end through fire(): FaultBlackhole at a send-site, and
    # shm_vanish unlinking the handoff's segment path.
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_serve_") as tmp:
        os.environ["RLT_FAULT"] = (
            "blackhole@point:beat,replica:decode-0,once:0;"
            "shm_vanish@point:handoff_send,rid:r-1,once:0"
        )
        try:
            inject.set_member("decode", "decode-0")
            try:
                inject.fire("beat")
                problems.append("serve fire: blackhole did not raise")
            except inject.FaultBlackhole:
                pass
            seg = os.path.join(tmp, "seg")
            with open(seg, "wb") as f:
                f.write(b"\x00" * 8)
            inject.fire("handoff_send", rid="r-2", path=seg)
            check(os.path.exists(seg),
                  "serve fire: shm_vanish hit the wrong rid")
            inject.fire("handoff_send", rid="r-1", path=seg)
            check(not os.path.exists(seg),
                  "serve fire: shm_vanish left the segment")
        finally:
            inject.set_member(None, None)
            os.environ.pop("RLT_FAULT", None)

    # Elastic world sizing: the lose_worker capacity oracle and the
    # governor's shrink/grow/reject decision logic (pure — no fits).
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_cap_") as tmp:
        specs = inject.parse_faults("lose_worker@point:spawn,rank:1,secs:5")
        check(specs[0].kind == "lose_worker" and specs[0].secs == 5.0,
              "grammar: lose_worker parse")
        inject.record_worker_loss(1, regain_s=None, state_dir=tmp)
        check(inject.lost_worker_count(state_dir=tmp) == 1,
              "capacity: permanent loss not counted")
        inject.record_worker_loss(2, regain_s=10.0, state_dir=tmp)
        check(inject.lost_worker_count(state_dir=tmp) == 2,
              "capacity: timed loss not counted")
        check(inject.lost_worker_count(
            now=time.time() + 60, state_dir=tmp) == 1,
            "capacity: regained worker still counted")

    from ray_lightning_tpu.parallel.strategies import RayStrategy

    cap = [4]
    gov = RayStrategy(num_workers=4, max_restarts=1,
                      elastic_min_workers=2,
                      elastic_capacity_fn=lambda: cap[0])
    check(gov._elastic_resize_decision() == (4, False),
          "governor: full capacity must not resize")
    cap[0] = 3
    check(gov._elastic_resize_decision() == (3, False),
          "governor: shrink target wrong")
    cap[0] = 1
    check(gov._elastic_resize_decision() == (1, True),
          "governor: below elastic_min_workers not rejected")
    fixed = RayStrategy(num_workers=4, max_restarts=1)
    check(fixed._elastic_resize_decision() == (None, False),
          "governor: fixed-size strategy must never resize")
    return problems


# ---------------------------------------------------------------------------
# Full matrix: real fits with injected faults
# ---------------------------------------------------------------------------

# (name, RLT_FAULT value, strategy overrides) — each scenario trains
# 3 epochs x 2 batches on the boring model and must complete with
# global_step == 6 after recovering.
_MATRIX = [
    ("crash", "crash@step:3,rank:0", {}),
    ("spawn-crash", "crash@point:spawn,rank:0", {}),
    ("sigterm-preempt", "sigterm@step:3,rank:0", {}),
    ("hang-abort", "hang@step:3,rank:0,secs:120", {
        "telemetry": {"tier": "cheap", "heartbeat_s": 0.2},
        "monitor": {"hang_intervals": 2, "abort_after_s": 0.5},
    }),
    ("torn-ckpt", "torn@point:ckpt_write,nth:2,rank:0;crash@step:5,rank:0",
     {}),
    ("bitflip-ckpt",
     "bitflip@point:ckpt_write,nth:2,rank:0;crash@step:5,rank:0", {}),
]


def _run_scenario(name: str, fault: str, overrides: dict,
                  workers: int) -> dict:
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    out = {"name": name, "ok": False, "error": "", "events": [],
           "restarts": 0, "preempts": 0, "wall_s": 0.0}
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix=f"rlt_chaos_{name}_") as tmp:
        os.environ["RLT_FAULT"] = fault
        os.environ["RLT_FAULT_STATE"] = os.path.join(tmp, "chaos-state")
        try:
            strategy = RayStrategy(
                num_workers=workers, max_restarts=1,
                restart_backoff_s=0.05, **overrides,
            )
            trainer = Trainer(
                strategy=strategy, max_epochs=3, default_root_dir=tmp,
                limit_train_batches=2, limit_val_batches=1,
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            out["events"] = sorted({
                e["kind"] for e in trainer.monitor_report.get("events", [])
            })
            out["restarts"] = strategy.restarts_used
            out["preempts"] = strategy.preempt_restarts_used
            if trainer.global_step != 6:
                out["error"] = (
                    f"global_step {trainer.global_step} != 6"
                )
            elif name == "sigterm-preempt" and strategy.restarts_used:
                out["error"] = "preemption consumed the restart budget"
            else:
                out["ok"] = True
        except Exception as e:  # noqa: BLE001 - scorecard, not traceback
            out["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


# ---------------------------------------------------------------------------
# Elastic world-size matrix (shrink, shrink→grow, shrink-below-min)
# ---------------------------------------------------------------------------

def _run_elastic_shrink(workers_unused: int) -> dict:
    """A real 2-worker fit loses worker 1 at spawn (``lose_worker``):
    the governor must respawn with the 1 survivor (budget-free), finish
    with the exact step count, and record a ``resize`` event whose
    ``recover_s`` is the scorecard's ``resize_time_to_recover_s``."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    out = {"name": "elastic-shrink", "ok": False, "error": "",
           "events": [], "restarts": 0, "preempts": 0, "resizes": 0,
           "resize_time_to_recover_s": None, "wall_s": 0.0}
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_shrink_") as tmp:
        os.environ["RLT_FAULT"] = "lose_worker@point:spawn,rank:1"
        os.environ["RLT_FAULT_STATE"] = os.path.join(tmp, "chaos")
        try:
            strategy = RayStrategy(
                num_workers=2, max_restarts=1, restart_backoff_s=0.05,
                elastic_min_workers=1,
            )
            trainer = Trainer(
                strategy=strategy, max_epochs=3, default_root_dir=tmp,
                limit_train_batches=2, limit_val_batches=1,
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            out["events"] = sorted({
                e["kind"] for e in trainer.monitor_report.get("events", [])
            })
            out["restarts"] = strategy.restarts_used
            out["preempts"] = strategy.preempt_restarts_used
            out["resizes"] = strategy.resizes_used
            out["resize_time_to_recover_s"] = (
                strategy.last_resize_recover_s
            )
            if trainer.global_step != 6:
                out["error"] = f"global_step {trainer.global_step} != 6"
            elif strategy.active_workers != 1:
                out["error"] = (
                    f"active_workers {strategy.active_workers} != 1"
                )
            elif strategy.restarts_used:
                out["error"] = "shrink consumed the restart budget"
            elif "resize" not in out["events"]:
                out["error"] = "no resize event recorded"
            else:
                out["ok"] = True
        except Exception as e:  # noqa: BLE001
            out["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


def _run_elastic_shrink_grow(workers_unused: int) -> dict:
    """Governor-level shrink→grow simulation: deterministic fake
    attempts drive run()'s recovery loop (a real grown attempt needs a
    multi-process mesh this container's CPU backend cannot train).
    World trace must read 2 → 1 → 2 with two resize events and no
    budget consumed."""
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.core.loop import FitConfig
    from ray_lightning_tpu.fault.drain import PreemptedError
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    out = {"name": "elastic-shrink-grow", "ok": False, "error": "",
           "events": [], "restarts": 0, "preempts": 0, "resizes": 0,
           "resize_time_to_recover_s": None, "wall_s": 0.0}
    t0 = time.monotonic()
    try:
        with tempfile.TemporaryDirectory(prefix="rlt_chaos_sg_") as tmp:
            cap = [1]  # worker 1 already lost when the fit starts
            strategy = RayStrategy(
                num_workers=2, max_restarts=1, restart_backoff_s=0.0,
                elastic_min_workers=1, elastic_grow_after_s=0.0,
                elastic_capacity_fn=lambda: cap[0],
            )
            strategy._backend = object()  # fakes below never touch it
            strategy._respawn_workers = lambda: None
            strategy._kill_workers = lambda *a, **k: None
            strategy._latest_restart_checkpoint = (
                lambda rd: {"path": None, "corrupt": []}
            )
            worlds = [strategy.active_workers]
            attempt = [0]

            def fake_run_once(*a, **k):
                attempt[0] += 1
                worlds.append(strategy.active_workers)
                if attempt[0] == 1:
                    raise ActorDiedError("worker 1 preempted")
                if attempt[0] == 2:
                    # capacity returned mid-attempt; the pump's grow
                    # arming drained the fleet
                    cap[0] = 2
                    strategy._grow_pending = True
                    raise PreemptedError(
                        "grow drain", step=5, reason="grow"
                    )
                return [{"rank": 0}]

            strategy._run_once = fake_run_once
            strategy.run(
                "fit", None, None,
                FitConfig(max_epochs=1, default_root_dir=tmp), [],
            )
            out["events"] = sorted({
                e["kind"] for e in strategy.recovery_events
            })
            out["restarts"] = strategy.restarts_used
            out["preempts"] = strategy.preempt_restarts_used
            out["resizes"] = strategy.resizes_used
            out["resize_time_to_recover_s"] = (
                strategy.last_resize_recover_s
            )
            trace = worlds[1:]  # world size seen by each attempt
            if trace != [2, 1, 2]:
                out["error"] = f"world trace {trace} != [2, 1, 2]"
            elif strategy.restarts_used:
                out["error"] = "shrink/grow consumed the restart budget"
            elif strategy.resizes_used != 2:
                out["error"] = f"resizes {strategy.resizes_used} != 2"
            else:
                out["ok"] = True
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


def _run_elastic_below_min(workers_unused: int) -> dict:
    """Capacity below ``elastic_min_workers`` must REJECT the shrink:
    the fit fails with the capacity arithmetic in the error, rather
    than training a crippled fleet."""
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    out = {"name": "elastic-below-min", "ok": False, "error": "",
           "events": [], "restarts": 0, "preempts": 0, "resizes": 0,
           "resize_time_to_recover_s": None, "wall_s": 0.0}
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_bm_") as tmp:
        os.environ["RLT_FAULT"] = "lose_worker@point:spawn,rank:1"
        os.environ["RLT_FAULT_STATE"] = os.path.join(tmp, "chaos")
        try:
            strategy = RayStrategy(
                num_workers=2, max_restarts=1, restart_backoff_s=0.05,
                elastic_min_workers=2,
            )
            trainer = Trainer(
                strategy=strategy, max_epochs=3, default_root_dir=tmp,
                limit_train_batches=2, limit_val_batches=1,
                enable_checkpointing=False,
            )
            try:
                trainer.fit(
                    BoringModel(), BoringDataModule(batch_size=16)
                )
                out["error"] = "fit completed despite capacity < min"
            except ActorDiedError as e:
                out["events"] = sorted({
                    ev["kind"] for ev in strategy.recovery_events
                })
                if "shrink rejected" not in str(e):
                    out["error"] = (
                        f"rejection not named in error: {e}"
                    )
                elif "resize_rejected" not in out["events"]:
                    out["error"] = "no resize_rejected event"
                elif strategy.active_workers != 2:
                    out["error"] = "world changed despite rejection"
                else:
                    out["ok"] = True
        except Exception as e:  # noqa: BLE001
            out["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


_ELASTIC_MATRIX = [
    ("elastic-shrink", _run_elastic_shrink),
    ("elastic-shrink-grow", _run_elastic_shrink_grow),
    ("elastic-below-min", _run_elastic_below_min),
]


def _print_scorecard(rows: list) -> None:
    width = max(len(r["name"]) for r in rows) + 2
    print(f"\n{'scenario':<{width}}{'result':<10}{'wall':<8}"
          f"{'restarts':<10}{'preempts':<10}{'resizes':<9}events")
    for r in rows:
        verdict = "RECOVERED" if r["ok"] else "FAILED"
        extra = ",".join(r["events"]) or "-"
        print(f"{r['name']:<{width}}{verdict:<10}{r['wall_s']:<8}"
              f"{r['restarts']:<10}{r['preempts']:<10}"
              f"{r.get('resizes', 0):<9}{extra}")
        if r.get("resize_time_to_recover_s") is not None:
            print(f"{'':<{width}}  resize_time_to_recover_s="
                  f"{r['resize_time_to_recover_s']}")
        if r["error"]:
            print(f"{'':<{width}}  {r['error']}")
    good = sum(r["ok"] for r in rows)
    print(f"\nchaos_sweep: {good}/{len(rows)} scenarios recovered")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Deterministic fault-injection sweep "
        "(docs/FAULT_TOLERANCE.md)."
    )
    ap.add_argument("--selftest", action="store_true",
                    help="fast chaos-plane self-checks only (no fits)")
    ap.add_argument("--workers", type=int, default=1,
                    help="worker actors per scenario (default 1; >1 "
                    "needs a backend whose mesh spans processes)")
    ap.add_argument("--only", default=None,
                    help="run a single scenario by name")
    args = ap.parse_args(argv)

    if args.selftest:
        problems = _selftest()
        for p in problems:
            print(f"chaos_sweep selftest: {p}", file=sys.stderr)
        print("chaos_sweep selftest: "
              + ("FAILED" if problems else "OK"))
        return 1 if problems else 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    rows = []
    for name, fault, overrides in _MATRIX:
        if args.only and name != args.only:
            continue
        print(f"chaos_sweep: running {name} ({fault}) ...", flush=True)
        rows.append(_run_scenario(name, fault, overrides, args.workers))
    for name, runner in _ELASTIC_MATRIX:
        if args.only and name != args.only:
            continue
        print(f"chaos_sweep: running {name} ...", flush=True)
        rows.append(runner(args.workers))
    _print_scorecard(rows)
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
