"""Chaos sweep: run the fault matrix end-to-end and print a recovery
scorecard.

Two modes:

* ``--selftest`` (wired into ``format.sh`` layer 5): fast,
  subprocess-free checks of the chaos plane itself — the ``RLT_FAULT``
  grammar, deterministic (point, rank, step, nth) matching,
  exactly-once markers, the torn/bit-flip file corruptors, and the
  checkpoint verifier catching what they break.  Seconds, zero
  accelerator work.
* default: the full acceptance matrix — for each fault kind a real
  multi-process fit (worker actors on the CPU-simulated mesh) with the
  fault injected deterministically, asserting the fit completes with
  the correct final step count and the right recovery events.  This is
  the same matrix ``tests/test_fault_tolerance.py`` runs under pytest
  (``-m chaos``); the tool form prints a scorecard and exits non-zero
  on any unrecovered scenario.

Usage::

    python tools/chaos_sweep.py --selftest
    python tools/chaos_sweep.py                  # full matrix, 1 worker
    python tools/chaos_sweep.py --workers 2      # multi-process mesh
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# --selftest: the chaos plane itself (no subprocesses, no jax fits)
# ---------------------------------------------------------------------------

def _selftest() -> list:
    problems: list = []

    def check(cond: bool, what: str) -> None:
        if not cond:
            problems.append(what)

    from ray_lightning_tpu.fault import inject

    # Grammar round-trip.
    specs = inject.parse_faults(
        "crash@step:7,rank:1;hang@step:5,secs:120;"
        "bitflip@point:ckpt_write,nth:2;sigterm@step:3,once:0"
    )
    check(len(specs) == 4, "grammar: expected 4 specs")
    check(specs[0].kind == "crash" and specs[0].step == 7
          and specs[0].rank == 1, "grammar: crash spec fields")
    check(specs[1].secs == 120.0, "grammar: secs parse")
    check(specs[2].point == "ckpt_write" and specs[2].nth == 2,
          "grammar: point/nth parse")
    check(specs[3].once is False, "grammar: once:0 parse")
    for bad in ("explode@step:1", "crash@step", "crash@wat:1",
                "crash@point:nowhere"):
        try:
            inject.parse_faults(bad)
            problems.append(f"grammar: {bad!r} should not parse")
        except ValueError:
            pass

    # Deterministic matching + exactly-once markers.
    with tempfile.TemporaryDirectory(prefix="rlt_chaos_") as tmp:
        plan = inject.FaultPlan(
            inject.parse_faults("exc@step:2,rank:0"), tmp
        )
        check(not plan.due("step", rank=0, step=1, epoch=0),
              "match: wrong step fired")
        check(not plan.due("step", rank=1, step=2, epoch=0),
              "match: wrong rank fired")
        due = plan.due("step", rank=0, step=2, epoch=0)
        check(len(due) == 1, "match: exact coordinates did not fire")
        plan.mark_fired(due[0])
        check(not plan.due("step", rank=0, step=2, epoch=0),
              "once: refired after marker")
        fresh = inject.FaultPlan(
            inject.parse_faults("exc@step:2,rank:0"), tmp
        )
        check(not fresh.due("step", rank=0, step=2, epoch=0),
              "once: marker did not survive a new plan (restart)")

        # nth occurrence counting.
        plan2 = inject.FaultPlan(
            inject.parse_faults("torn@point:ckpt_write,nth:2"), None
        )
        check(not plan2.due("ckpt_write", None, None, None),
              "nth: first occurrence fired")
        check(len(plan2.due("ckpt_write", None, None, None)) == 1,
              "nth: second occurrence did not fire")

        # Corruptors vs the checkpoint verifier.
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
            verify_stream_file,
        )

        import numpy as np

        path = os.path.join(tmp, "ck.ckpt")
        state_stream_to_file(
            to_state_stream({"w": np.arange(64, dtype=np.float32)}), path
        )
        check(verify_stream_file(path) == [], "verify: pristine flagged")
        inject._corrupt_bitflip(path)
        check(bool(verify_stream_file(path)), "verify: bitflip missed")
        state_stream_to_file(
            to_state_stream({"w": np.arange(64, dtype=np.float32)}), path
        )
        inject._corrupt_torn(path)
        check(bool(verify_stream_file(path)), "verify: torn missed")
    return problems


# ---------------------------------------------------------------------------
# Full matrix: real fits with injected faults
# ---------------------------------------------------------------------------

# (name, RLT_FAULT value, strategy overrides) — each scenario trains
# 3 epochs x 2 batches on the boring model and must complete with
# global_step == 6 after recovering.
_MATRIX = [
    ("crash", "crash@step:3,rank:0", {}),
    ("spawn-crash", "crash@point:spawn,rank:0", {}),
    ("sigterm-preempt", "sigterm@step:3,rank:0", {}),
    ("hang-abort", "hang@step:3,rank:0,secs:120", {
        "telemetry": {"tier": "cheap", "heartbeat_s": 0.2},
        "monitor": {"hang_intervals": 2, "abort_after_s": 0.5},
    }),
    ("torn-ckpt", "torn@point:ckpt_write,nth:2,rank:0;crash@step:5,rank:0",
     {}),
    ("bitflip-ckpt",
     "bitflip@point:ckpt_write,nth:2,rank:0;crash@step:5,rank:0", {}),
]


def _run_scenario(name: str, fault: str, overrides: dict,
                  workers: int) -> dict:
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    out = {"name": name, "ok": False, "error": "", "events": [],
           "restarts": 0, "preempts": 0, "wall_s": 0.0}
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix=f"rlt_chaos_{name}_") as tmp:
        os.environ["RLT_FAULT"] = fault
        os.environ["RLT_FAULT_STATE"] = os.path.join(tmp, "chaos-state")
        try:
            strategy = RayStrategy(
                num_workers=workers, max_restarts=1,
                restart_backoff_s=0.05, **overrides,
            )
            trainer = Trainer(
                strategy=strategy, max_epochs=3, default_root_dir=tmp,
                limit_train_batches=2, limit_val_batches=1,
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            out["events"] = sorted({
                e["kind"] for e in trainer.monitor_report.get("events", [])
            })
            out["restarts"] = strategy.restarts_used
            out["preempts"] = strategy.preempt_restarts_used
            if trainer.global_step != 6:
                out["error"] = (
                    f"global_step {trainer.global_step} != 6"
                )
            elif name == "sigterm-preempt" and strategy.restarts_used:
                out["error"] = "preemption consumed the restart budget"
            else:
                out["ok"] = True
        except Exception as e:  # noqa: BLE001 - scorecard, not traceback
            out["error"] = f"{type(e).__name__}: {e}"
        finally:
            os.environ.pop("RLT_FAULT", None)
            os.environ.pop("RLT_FAULT_STATE", None)
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


def _print_scorecard(rows: list) -> None:
    width = max(len(r["name"]) for r in rows) + 2
    print(f"\n{'scenario':<{width}}{'result':<10}{'wall':<8}"
          f"{'restarts':<10}{'preempts':<10}events")
    for r in rows:
        verdict = "RECOVERED" if r["ok"] else "FAILED"
        extra = ",".join(r["events"]) or "-"
        print(f"{r['name']:<{width}}{verdict:<10}{r['wall_s']:<8}"
              f"{r['restarts']:<10}{r['preempts']:<10}{extra}")
        if r["error"]:
            print(f"{'':<{width}}  {r['error']}")
    good = sum(r["ok"] for r in rows)
    print(f"\nchaos_sweep: {good}/{len(rows)} scenarios recovered")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Deterministic fault-injection sweep "
        "(docs/FAULT_TOLERANCE.md)."
    )
    ap.add_argument("--selftest", action="store_true",
                    help="fast chaos-plane self-checks only (no fits)")
    ap.add_argument("--workers", type=int, default=1,
                    help="worker actors per scenario (default 1; >1 "
                    "needs a backend whose mesh spans processes)")
    ap.add_argument("--only", default=None,
                    help="run a single scenario by name")
    args = ap.parse_args(argv)

    if args.selftest:
        problems = _selftest()
        for p in problems:
            print(f"chaos_sweep selftest: {p}", file=sys.stderr)
        print("chaos_sweep selftest: "
              + ("FAILED" if problems else "OK"))
        return 1 if problems else 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    rows = []
    for name, fault, overrides in _MATRIX:
        if args.only and name != args.only:
            continue
        print(f"chaos_sweep: running {name} ({fault}) ...", flush=True)
        rows.append(_run_scenario(name, fault, overrides, args.workers))
    _print_scorecard(rows)
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
