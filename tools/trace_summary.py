"""Summarize ANY run's exported Chrome trace: top ops + phase totals.

Usage::

    python tools/trace_summary.py <trace-dir-or-file> [--top 25]
        [--keep-host] [--per-step N]

Accepts what the framework's exporters actually produce:

* a ``jax.profiler`` capture directory (``ProfilerCallback`` /
  ``tools/profile_step.py`` — newest ``*.trace.json.gz`` wins);
* a single Chrome-trace file, gzipped or plain — including the telemetry
  span export (``telemetry/trace-rank0.json``).

Where ``profile_step.py`` is the bespoke profile *harness* (it runs the
model, then summarizes), this tool is the summarize-only half for traces
somebody else already recorded — a production fit, a ProfilerCallback
window, a collected artifact from another host.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_lightning_tpu.telemetry.trace_parse import (  # noqa: E402
    bucket_totals,
    collect,
    collect_file,
    top_ops,
)


def summarize(durs: dict, top: int = 25, per_step: int = 1) -> str:
    total = sum(durs.values())
    if not total:
        return "(trace holds no ph=='X' duration events)"
    lines = ["== buckets (% of op time) =="]
    for b, d in sorted(bucket_totals(durs).items(), key=lambda kv: -kv[1]):
        lines.append(
            f"{100 * d / total:6.2f}%  {d / 1e3 / per_step:10.3f} "
            f"ms/step  {b}"
        )
    lines.append(f"== top {top} ops ==")
    for name, d in top_ops(durs, top):
        lines.append(
            f"{100 * d / total:6.2f}%  {d / 1e3 / per_step:10.3f} "
            f"ms/step  {name[:88]}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a Chrome trace (jax.profiler capture dir "
        "or a single trace file, incl. telemetry span exports)."
    )
    ap.add_argument("path", help="trace directory or .json/.json.gz file")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--per-step", type=int, default=1,
                    help="steps captured in the trace (normalizes ms/step)")
    ap.add_argument("--keep-host", action="store_true",
                    help="keep host-side python/runtime events too")
    args = ap.parse_args(argv)

    if os.path.isdir(args.path):
        durs = collect(args.path, keep_host=args.keep_host)
    else:
        durs = collect_file(args.path, keep_host=args.keep_host)
    if not durs:
        print("no events matched (try --keep-host for host-only traces)")
        return 1
    print(summarize(durs, top=args.top, per_step=max(args.per_step, 1)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
